"""End-to-end hot swaps through a live ScoringService (jax + smoke).

The acceptance contract, machine-checked:

* a same-shape candidate swaps in with ZERO recompilation and post-swap
  scores are bitwise the new generation's direct forward_inference;
* every response under concurrent score()/swap traffic carries ONE
  self-consistent generation — its scores reproduce that generation's
  program bit-for-bit (no torn encoder/scorer reads);
* a swap EMPTIES effective cache hits (generation mismatch = miss) instead
  of scoring old hidden states through new weights;
* a grown catalog publishes as a recompiled generation and serves the new
  item ids while the old generation stays pinned for rollback;
* chaos mid-swap (injected engine faults) rides the degradation ladder —
  the service keeps answering, degraded at worst;
* the SLO-guarded controller promotes a clean candidate and rolls a forced
  breach back exactly once, end to end.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.nn.sequential.sasrec import SasRec
from replay_tpu.nn.vocabulary import resize_item_embeddings
from replay_tpu.obs.slo import SLORule
from replay_tpu.serve import FallbackScorer, PromotionController, ScoringService, make_window
from replay_tpu.serve.errors import ServeError
from replay_tpu.utils.faults import EngineErrorAt, wrap_method

pytestmark = [pytest.mark.jax, pytest.mark.smoke]

NUM_ITEMS, SEQ_LEN, DIM = 20, 8, 8


class RecordingLogger:
    def __init__(self):
        self.events = []

    def log_event(self, event):
        self.events.append(event)

    def close(self):
        pass

    def named(self, name):
        return [e for e in self.events if e.event == name]


def make_model(num_items=NUM_ITEMS):
    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id", FeatureType.CATEGORICAL, is_seq=True,
            feature_hint=FeatureHint.ITEM_ID, cardinality=num_items,
            embedding_dim=DIM,
        )
    )
    model = SasRec(
        schema=schema, embedding_dim=DIM, num_blocks=1, max_sequence_length=SEQ_LEN
    )
    ids = np.zeros((2, SEQ_LEN), np.int32)
    params = model.init(
        jax.random.PRNGKey(0), {"item_id": ids}, np.ones((2, SEQ_LEN), bool)
    )["params"]
    return model, jax.tree.map(np.asarray, params)


def perturb(params, scale):
    """A same-shape candidate: every leaf scaled (different scores, same tree)."""
    return jax.tree.map(lambda x: (np.asarray(x) * scale).astype(x.dtype), params)


def direct_scores(model, params, items, length_bucket, batch_bucket):
    """The generation's own program: AOT forward_inference at the routed
    (length, batch) bucket — what a response must reproduce bit-for-bit."""

    def fwd(p, ids, mask):
        return model.apply(
            {"params": p}, {"item_id": ids}, mask, method=SasRec.forward_inference
        )

    program = (
        jax.jit(fwd)
        .lower(
            params,
            jax.ShapeDtypeStruct((batch_bucket, length_bucket), jnp.int32),
            jax.ShapeDtypeStruct((batch_bucket, length_bucket), jnp.bool_),
        )
        .compile()
    )
    window, mask, _ = make_window(items, length_bucket)
    ids = np.stack([window] * batch_bucket)
    masks = np.stack([mask] * batch_bucket)
    return np.asarray(program(params, ids, masks))[0]


@pytest.fixture()
def service_setup():
    model, params = make_model()
    logger = RecordingLogger()
    service = ScoringService(
        model, params,
        length_buckets=(SEQ_LEN,),
        batch_buckets=(1, 4),
        max_wait_ms=10.0,
        logger=logger,
    )
    with service:
        yield model, params, service, logger


def lane_buckets(response):
    """(length_bucket, batch_bucket) a response's scores were computed at."""
    lane = response.lane.split("#", 1)[0]
    assert lane.startswith("encode:L=")
    return int(lane.split("=", 1)[1]), response.batch_bucket


class TestHotSwap:
    def test_same_shape_swap_is_recompile_free_and_bitwise(self, service_setup):
        model, params, service, logger = service_setup
        history = [3, 5, 7, 2]
        before = service.score("u1", history=history, timeout=30)
        assert before.generation == 0 and before.role == "stable"
        np.testing.assert_array_equal(
            before.scores, direct_scores(model, params, history, *lane_buckets(before))
        )

        candidate = perturb(params, 1.01)
        generation = service.publish_candidate(candidate, label="v1")
        publishes = logger.named("on_publish")
        assert len(publishes) == 1
        assert publishes[0].payload["recompiled"] is False  # same shapes: zero recompile
        assert service.store.generation(generation).engine is None  # shared executables

        info = service.promote(generation)
        assert info == {"from_generation": 0, "to_generation": generation}
        swaps = logger.named("on_swap")
        assert len(swaps) == 1 and swaps[0].payload["reason"] == "promote"

        after = service.score("u1", history=history, timeout=30)
        assert after.generation == generation
        np.testing.assert_array_equal(
            after.scores, direct_scores(model, candidate, history, *lane_buckets(after))
        )
        assert not np.array_equal(before.scores, after.scores)

    def test_swap_empties_effective_hits(self, service_setup):
        """Satellite regression: cached embeddings were encoded by the OLD
        generation — after a swap the pure-hit path MISSES (re-encode) and
        never mixes an old hidden state with the new scorer."""
        model, params, service, logger = service_setup
        history = [1, 2, 3]
        service.score("u2", history=history, timeout=30)
        hit = service.score("u2", timeout=30)  # warmed: a true pure hit
        assert hit.served_from == "hit" and hit.generation == 0

        candidate = perturb(params, 0.99)
        generation = service.publish_candidate(candidate)
        service.promote(generation)

        post = service.score("u2", timeout=30)
        # the cached embedding certified generation 0: MISS, re-encode, and
        # the response is entirely the new generation's math
        assert post.served_from != "hit"
        assert post.generation == generation
        np.testing.assert_array_equal(
            post.scores, direct_scores(model, candidate, history, *lane_buckets(post))
        )
        assert service.stats()["generation_misses"] >= 1

        rewarmed = service.score("u2", timeout=30)
        assert rewarmed.served_from == "hit"  # re-encoded under the new generation
        assert rewarmed.generation == generation

    def test_concurrent_scores_always_carry_one_consistent_generation(
        self, service_setup
    ):
        """Swap atomicity under concurrent score() threads: every response's
        generation tag reproduces that generation's program bitwise — a batch
        torn across a swap could not match any single generation."""
        model, params, service, logger = service_setup
        all_params = {0: params}
        histories = {
            f"user-{i}": [int(x) for x in np.random.default_rng(i).integers(1, NUM_ITEMS, 4)]
            for i in range(6)
        }
        responses = []
        responses_lock = threading.Lock()
        stop = threading.Event()
        failures = []

        def client(user):
            while not stop.is_set():
                try:
                    response = service.score(user, history=histories[user], timeout=30)
                except ServeError as exc:  # pragma: no cover - would fail below
                    failures.append(exc)
                    return
                with responses_lock:
                    responses.append((user, response))

        def answered_count():
            with responses_lock:
                return len(responses)

        threads = [threading.Thread(target=client, args=(u,)) for u in histories]
        for t in threads:
            t.start()
        import time as _time

        for swap in range(1, 5):
            # let real traffic land BETWEEN swaps so both sides of each swap
            # are observed under load
            target = answered_count() + 6
            deadline = _time.monotonic() + 10.0
            while answered_count() < target and _time.monotonic() < deadline:
                _time.sleep(0.005)
            candidate = perturb(params, 1.0 + 0.01 * swap)
            generation = service.publish_candidate(candidate)
            all_params[generation] = candidate
            service.promote(generation)
        stop.set()
        for t in threads:
            t.join()

        assert not failures  # zero request errors across every swap
        assert len(responses) > 10
        seen_generations = {r.generation for _, r in responses}
        assert len(seen_generations) >= 2  # the swaps were observed mid-load
        cache = {}
        for user, response in responses:
            assert response.generation in all_params
            key = (user, response.generation, lane_buckets(response))
            if key not in cache:
                cache[key] = direct_scores(
                    model,
                    all_params[response.generation],
                    histories[user],
                    *lane_buckets(response),
                )
            np.testing.assert_array_equal(response.scores, cache[key])

    def test_grown_catalog_publishes_recompiled_and_serves_new_items(
        self, service_setup
    ):
        model, params, service, logger = service_setup
        grown = resize_item_embeddings(
            jax.tree.map(np.asarray, params), model.schema, NUM_ITEMS + 4
        )
        generation = service.publish_candidate(grown, label="grown")
        publish = logger.named("on_publish")[-1].payload
        assert publish["recompiled"] is True
        assert "embedding" in publish["recompile_reason"]
        assert service.store.generation(generation).engine is not None

        service.promote(generation)
        new_item = NUM_ITEMS + 2  # an id that did not exist at construction
        response = service.score("grown-user", history=[1, new_item], timeout=30)
        assert response.generation == generation
        assert response.scores.shape[-1] == NUM_ITEMS + 4  # the grown catalog
        # the old generation stays pinned: rollback restores the old catalog
        service.rollback()
        back = service.score("rollback-user", history=[1, 2], timeout=30)
        assert back.generation == 0
        assert back.scores.shape[-1] == NUM_ITEMS


class TestCanaryRouting:
    def test_slice_serves_candidate_rest_serves_stable(self, service_setup):
        from replay_tpu.serve import in_canary_slice

        model, params, service, logger = service_setup
        candidate = perturb(params, 1.02)
        generation = service.publish_candidate(candidate)
        service.begin_canary(generation, fraction=0.5)
        users = [f"canary-user-{i}" for i in range(12)]
        for user in users:
            response = service.score(user, history=[2, 4, 6], timeout=30)
            if in_canary_slice(user, 0.5):
                assert response.role == "candidate"
                assert response.generation == generation
                np.testing.assert_array_equal(
                    response.scores,
                    direct_scores(model, candidate, [2, 4, 6], *lane_buckets(response)),
                )
            else:
                assert response.role == "stable"
                assert response.generation == 0
        roles = service.canary_stats()
        assert roles["candidate"]["answered"] > 0
        assert roles["stable"]["answered"] > 0

    def test_publish_during_canary_refused_and_routing_stays_pinned(
        self, service_setup
    ):
        """A publish racing a live canary must not redirect the slice: the
        controller refuses it outright, and even a low-level
        service.publish_candidate leaves canary traffic on the PINNED
        generation (never a just-published unvetted candidate)."""
        model, params, service, logger = service_setup
        controller = PromotionController(
            service, promote_after=99, min_canary_requests=1, fraction=1.0
        )
        pinned = controller.publish(perturb(params, 1.01), label="pinned")
        controller.begin_canary()
        with pytest.raises(RuntimeError, match="active canary"):
            controller.publish(perturb(params, 1.02), label="racer")
        # low-level publish is allowed (it only registers a candidate) —
        # but the canary slice keeps serving the pinned generation
        racer = service.publish_candidate(perturb(params, 1.03), label="low-level")
        response = service.score("pin-user", history=[1, 2], timeout=30)
        assert response.role == "candidate"
        assert response.generation == pinned
        assert response.generation != racer
        # the candidate ROLE without a canary (shadow probing) still
        # addresses the store's latest candidate
        probe = service.submit(
            "probe-user", history=[3, 4], _role="candidate"
        ).result(timeout=30)
        assert probe.generation == pinned  # canary active: pin wins even here
        service.end_canary()
        probe2 = service.submit(
            "probe-user-2", history=[3, 4], _role="candidate"
        ).result(timeout=30)
        assert probe2.generation == racer  # no canary: shadow probe, latest

    def test_stale_epoch_outcomes_do_not_pollute_the_new_canary_window(
        self, service_setup
    ):
        """A previous candidate's in-flight request (older canary epoch)
        landing after begin_canary must not count in the fresh window."""
        model, params, service, logger = service_setup
        first = service.publish_candidate(perturb(params, 1.01))
        service.begin_canary(first, fraction=1.0)
        service.score("epoch-user", history=[1, 2], timeout=30)
        assert service.canary_stats()["candidate"]["answered"] == 1
        service.rollback()
        second = service.publish_candidate(perturb(params, 1.02))
        service.begin_canary(second, fraction=1.0)
        # fresh window starts clean…
        assert service.canary_stats()["candidate"]["answered"] == 0
        # …and an old-epoch pending resolving NOW is not counted against it
        from replay_tpu.serve.request import PendingRequest

        stale = PendingRequest(request=None, future=None, served_from="hit", role="candidate")
        stale.canary_epoch = service._canary_epoch - 1
        assert not service._counts_for_role("candidate", stale)
        fresh = PendingRequest(request=None, future=None, served_from="hit", role="candidate")
        fresh.canary_epoch = service._canary_epoch
        assert service._counts_for_role("candidate", fresh)

    def test_controller_promotes_clean_candidate_end_to_end(self, service_setup):
        model, params, service, logger = service_setup
        controller = PromotionController(
            service, promote_after=2, min_canary_requests=1, fraction=1.0
        )
        generation = controller.publish(perturb(params, 1.01), label="clean")
        controller.begin_canary()
        for _ in range(2):
            service.score("ct-user", history=[1, 2, 3], timeout=30)
            controller.evaluate()
        assert controller.stage == "promoted"
        assert service.store.stable_generation == generation
        assert len(logger.named("on_promotion")) == 1
        # post-promotion, EVERYONE serves the new generation
        assert service.score("other", history=[5], timeout=30).generation == generation

    def test_forced_breach_rolls_back_once_and_service_keeps_answering(
        self, service_setup
    ):
        model, params, service, logger = service_setup
        # a rule that breaches on ANY canary evaluation with data — the
        # deterministic forced-breach lever the canary_smoke CI job also uses
        controller = PromotionController(
            service,
            rules=(SLORule("replay_canary_requests", ">=", 0.0, name="forced"),),
            promote_after=99,
            min_canary_requests=1,
            fraction=1.0,
        )
        generation = controller.publish(perturb(params, 1.5), label="bad")
        controller.begin_canary()
        service.score("fb-user", history=[1, 2], timeout=30)
        record = controller.evaluate()
        assert record["action"] == "rollback"
        assert controller.stage == "rolled_back"
        assert len(logger.named("on_rollback")) == 1
        assert service.store.stable_generation == 0
        # exactly ONE rollback incident; the service answers on the restored gen
        for _ in range(3):
            controller.evaluate()
        assert len(logger.named("on_rollback")) == 1
        response = service.score("fb-user-2", history=[3, 4], timeout=30)
        assert response.generation == 0
        history_events = [e["event"] for e in service.generation_history()]
        assert history_events.count("rolled_back") == 1


class TestChaosMidSwap:
    def test_engine_fault_mid_swap_rides_the_ladder(self):
        """EngineErrorAt hits while a canary is live: the breaker opens, the
        ladder answers (cache_only / fallback), nothing hangs, and after the
        faults clear the service promotes normally."""
        from replay_tpu.serve import CircuitBreaker

        model, params = make_model()
        logger = RecordingLogger()
        fallback = FallbackScorer(np.arange(NUM_ITEMS + 1, dtype=np.float64))
        service = ScoringService(
            model, params,
            length_buckets=(SEQ_LEN,),
            batch_buckets=(1, 4),
            max_wait_ms=5.0,
            logger=logger,
            fallback=fallback,
            breaker=CircuitBreaker(failure_threshold=2, reset_timeout_s=0.05),
        )
        with service:
            # warm a user so the cache_only rung has material
            service.score("chaos-user", history=[1, 2, 3], timeout=30)
            generation = service.publish_candidate(perturb(params, 1.01))
            service.begin_canary(generation, fraction=1.0)

            injector = EngineErrorAt(at_calls=range(3))
            original = wrap_method(service.engine, "encode", injector)
            outcomes = []
            for i in range(6):
                try:
                    response = service.score("chaos-user", new_items=[4], timeout=30)
                    outcomes.append(response.served_by)
                except Exception as exc:  # noqa: BLE001 — the breaker's trip
                    outcomes.append(type(exc).__name__)
            service.engine.encode = original
            # every request RESOLVED (failed fast or answered — none hung);
            # the injected faults tripped the breaker and the ladder took over
            assert len(outcomes) == 6
            assert len(injector.injected_at) <= 3
            assert "cache_only" in outcomes or "fallback" in outcomes
            # faults cleared: the canary still promotes
            deadline = __import__("time").monotonic() + 5.0
            while __import__("time").monotonic() < deadline:
                response = service.score("chaos-user", new_items=[5], timeout=30)
                if response.served_by == "primary":
                    break
            assert response.served_by == "primary"
            service.promote(generation)
            final = service.score("chaos-user", new_items=[6], timeout=30)
            assert final.generation == generation
        stats = service.stats()
        # the only errors are the injected trips — the swap itself cost none
        assert stats["errors"] <= len(injector.injected_at)
