"""The quality plane through a live ScoringService (jax + smoke).

The acceptance contract, machine-checked:

* **online == offline** — the monitor's cumulative prequential hitrate@k /
  MRR@k / NDCG@k over a replayed advance log equal the offline
  ``metrics/ranking.py`` batteries evaluated on the SAME (slate, labels)
  pairs, to float tolerance;
* **drift fires exactly once** — an injected preference shift (uniform →
  all-head labels) trips the ``replay_drift_psi_series{series=interactions}``
  SLO rule through the service's own watchdog exactly once, latched under
  sustained shift, and the quality gauges are federation-visible on
  ``/snapshot``;
* **quality-gated canary** — a canary whose ONLINE quality breaches a
  :func:`canary_quality_rules` floor is rolled back by the
  PromotionController even though its error rate is zero, and the decision
  record carries the quality evidence.
"""

import json
import urllib.request

import numpy as np
import pytest

import jax

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.metrics import MRR, NDCG, HitRate
from replay_tpu.nn.sequential.sasrec import SasRec
from replay_tpu.obs import PopularityDescriptor, QualityMonitor, SLORule
from replay_tpu.obs.quality import canary_quality_rules
from replay_tpu.serve import PromotionController, ScoringService, top_k_cut

pytestmark = [pytest.mark.jax, pytest.mark.smoke]

NUM_ITEMS, SEQ_LEN, DIM = 20, 8, 8
K = 5


@pytest.fixture(scope="module")
def model_and_params():
    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id", FeatureType.CATEGORICAL, is_seq=True,
            feature_hint=FeatureHint.ITEM_ID, cardinality=NUM_ITEMS, embedding_dim=DIM,
        )
    )
    model = SasRec(
        schema=schema, embedding_dim=DIM, num_blocks=1, max_sequence_length=SEQ_LEN
    )
    ids = np.zeros((2, SEQ_LEN), np.int32)
    params = model.init(
        jax.random.PRNGKey(0), {"item_id": ids}, np.ones((2, SEQ_LEN), bool)
    )["params"]
    return model, jax.tree.map(np.asarray, params)


def _service(model_and_params, **kwargs):
    model, params = model_and_params
    kwargs.setdefault("length_buckets", (SEQ_LEN,))
    kwargs.setdefault("batch_buckets", (1, 4))
    kwargs.setdefault("max_wait_ms", 5.0)
    return ScoringService(model, params, **kwargs)


def _descriptor(rng):
    """A train log with a clear popularity head: item 1 is consumed by every
    user, the rest by one user each — the shift injector's target."""
    train = {user: [1, 2 + (user % (NUM_ITEMS - 2))] for user in range(10)}
    train[0].extend(int(x) for x in rng.integers(2, NUM_ITEMS, 4))
    return PopularityDescriptor.from_train(train, num_items=NUM_ITEMS)


class RecordingLogger:
    def __init__(self):
        self.events = []

    def log_event(self, event):
        self.events.append(event)

    def close(self):
        pass

    def named(self, name):
        return [e for e in self.events if e.event == name]


def perturb(params, scale):
    return jax.tree.map(lambda x: (np.asarray(x) * scale).astype(x.dtype), params)


def _scrape(service, path="/metrics"):
    url = service.metrics_exporter.url
    with urllib.request.urlopen(f"{url}{path}", timeout=10) as response:
        return response.read().decode()


# ---------------------------------------------------------------------------
# online == offline
# ---------------------------------------------------------------------------


def test_online_prequential_reconciles_with_offline_ranking(model_and_params):
    """Replay a deterministic advance log through the service; every
    prequential join (previous served slate vs the labels that just arrived)
    becomes one offline query — the monitor's cumulative online metrics must
    equal HitRate/MRR/NDCG on that log to float tolerance."""
    rng = np.random.default_rng(7)
    monitor = QualityMonitor(_descriptor(rng), k=K, emit_every=8)
    service = _service(model_and_params, quality=monitor)
    users = [f"rec-{i}" for i in range(6)]
    last_slate = {}
    recs, gt = {}, {}
    with service:
        for user in users:
            history = [int(x) for x in rng.integers(1, NUM_ITEMS, 4)]
            response = service.score(user, history=history, timeout=30)
            ids, _ = top_k_cut(response, K)
            last_slate[user] = [int(i) for i in ids]
        join_id = 0
        for _ in range(5):
            for index, user in enumerate(users):
                slate = last_slate[user]
                if index == 0:
                    labels = [slate[2]]  # guaranteed hit
                elif index == 1:
                    labels = [  # guaranteed miss
                        min(set(range(1, NUM_ITEMS)) - set(slate))
                    ]
                else:
                    labels = [int(x) for x in rng.integers(1, NUM_ITEMS, 2)]
                recs[join_id] = list(slate)
                gt[join_id] = list(labels)
                join_id += 1
                response = service.score(user, new_items=labels, timeout=30)
                ids, _ = top_k_cut(response, K)
                last_slate[user] = [int(i) for i in ids]
        snapshot = monitor.snapshot()
    stable = snapshot["roles"]["stable"]
    assert stable["joins"] == len(recs) == 30
    offline_hit = HitRate(K)(recs, gt)[f"HitRate@{K}"]
    offline_mrr = MRR(K)(recs, gt)[f"MRR@{K}"]
    offline_ndcg = NDCG(K)(recs, gt)[f"NDCG@{K}"]
    # the forced hit/miss rows keep the reconciliation non-degenerate
    assert 0.0 < offline_hit < 1.0
    assert stable["online_hitrate_cum"] == pytest.approx(offline_hit, abs=1e-12)
    assert stable["online_mrr_cum"] == pytest.approx(offline_mrr, abs=1e-12)
    assert stable["online_ndcg_cum"] == pytest.approx(offline_ndcg, abs=1e-12)


# ---------------------------------------------------------------------------
# drift through the watchdog
# ---------------------------------------------------------------------------


def test_injected_shift_trips_the_drift_slo_exactly_once(model_and_params):
    rng = np.random.default_rng(11)
    monitor = QualityMonitor(
        _descriptor(rng), k=K, window=64, emit_every=4,
        drift_reference=24, drift_window=12, drift_min_window=4,
        drift_threshold=1.5,
    )
    # gate the DIRECTLY injected series: under a sustained shift its window
    # only gains head items, so the PSI climb is monotone — one crossing
    rule = SLORule(
        "replay_drift_psi_series", ">", 1.5,
        for_steps=2, labels={"series": "interactions"}, name="drift_psi",
    )
    service = _service(
        model_and_params, metrics_port=0, quality=monitor, slo_rules=[rule]
    )
    with service:
        registry = service.metrics_registry

        def violations():
            return registry.value(
                "replay_slo_violations_total", labels={"rule": "drift_psi"}
            ) or 0.0

        # anchor each session (new_items needs a cached window to advance)
        for i in range(8):
            service.score(f"drift-{i}", history=[1 + (i % (NUM_ITEMS - 1))], timeout=30)
        # phase A: stationary labels (item 2, a fixed mid-popularity item) —
        # the distribution the reference freezes on; PSI stays ~0
        for i in range(40):
            service.score(f"drift-{i % 8}", new_items=[2], timeout=30)
        assert violations() == 0.0
        psi_before = monitor.snapshot()["drift"].get("interactions")
        assert psi_before is not None and psi_before < 1.5

        # phase B: every incoming label lands on the popularity head
        for i in range(40):
            service.score(f"drift-{i % 8}", new_items=[1], timeout=30)
        assert violations() == 1.0
        psi_after = monitor.snapshot()["drift"]["interactions"]
        assert psi_after > 1.5

        # sustained shift: the breach stays active, never re-fires
        for i in range(16):
            service.score(f"drift-{i % 8}", new_items=[1], timeout=30)
        assert violations() == 1.0
        assert monitor.drift_warnings >= 1

        # federation-visible: the labeled quality/drift gauges ride /snapshot
        snapshot = json.loads(_scrape(service, "/snapshot"))
        assert any(key.startswith("replay_quality_online_hitrate") for key in snapshot)
        assert any(key.startswith("replay_drift_psi_series") for key in snapshot)
        text = _scrape(service)
        assert "replay_drift_psi" in text
        assert "replay_quality_coverage" in text


# ---------------------------------------------------------------------------
# quality-gated canary
# ---------------------------------------------------------------------------


def test_quality_degraded_canary_rolls_back(model_and_params):
    """A canary with ZERO errors but degraded online quality: the
    canary_quality_rules floor (set impossibly high, the deterministic lever)
    breaches on the candidate slice and the controller rolls back."""
    model, params = model_and_params
    rng = np.random.default_rng(13)
    monitor = QualityMonitor(_descriptor(rng), k=K, emit_every=1)
    logger = RecordingLogger()
    service = _service(
        model_and_params, metrics_port=0, quality=monitor, logger=logger
    )
    with service:
        controller = PromotionController(
            service,
            rules=canary_quality_rules(min_online_hitrate=2.0, for_steps=1),
            promote_after=99,
            min_canary_requests=1,
            fraction=1.0,
        )
        generation = controller.publish(perturb(params, 1.01), label="stale")
        controller.begin_canary()
        # the candidate serves a slate, then the user's next advance joins it
        # — the candidate-slice online_hitrate gauge now EXISTS (and is <= 1)
        service.score("cq-user", history=[1, 2, 3], timeout=30)
        service.score("cq-user", new_items=[4], timeout=30)
        record = controller.evaluate()
        assert record["action"] == "rollback"
        assert "canary_online_hitrate" in record["breached_rules"]
        assert record["error_rate"] == 0.0
        # the decision record carries its quality evidence
        assert record["quality"]["joins"] >= 1
        assert record["quality"]["online_hitrate_cum"] <= 1.0
        assert controller.stage == "rolled_back"
        assert len(logger.named("on_rollback")) == 1
        assert service.store.stable_generation == 0
        evals = logger.named("on_canary_eval")
        assert evals and "quality" in evals[-1].payload
        # rolled back, not wedged: the service keeps answering on stable
        response = service.score("cq-user-2", history=[5, 6], timeout=30)
        assert response.generation == 0
    assert generation != 0
