"""int8 serving retrieval — the precision ladder's serving rung.

Round-trip quality gates on a synthetic catalog (ISSUE 11 acceptance):
recall@100 of the quantized candidate sweep vs f32 MIPS ≥ 0.99, the re-ranked
``CandidatePipeline`` top-k agreeing with the f32 pipeline on the same
candidates (the exact-f32-rescore stage makes the quantization error pick
candidates only, never rank them), table payload ≈ ¼ of f32, and the sharded
``[I/n, E]`` layout reproducing the unsharded search bit-for-bit.

The smoke test leaves ``REPLAY_TPU_RUN_DIR/precision_smoke/quant_gate.json``
for the CI ``precision_smoke`` gate.
"""

import json
import os

import numpy as np
import pytest

from replay_tpu.serve.quant import (
    QuantizedTable,
    quantization_error,
    quantize_embeddings,
)

NUM_ITEMS = 2000
DIM = 64
QUERIES = 128


@pytest.fixture(scope="module")
def catalog():
    rng = np.random.default_rng(0)
    # realistic spread: per-item norms vary (popular items larger) — the
    # per-ROW scales are what keeps the tail's resolution
    table = rng.normal(size=(NUM_ITEMS, DIM)).astype(np.float32)
    table *= rng.lognormal(0.0, 0.4, size=(NUM_ITEMS, 1)).astype(np.float32)
    queries = rng.normal(size=(QUERIES, DIM)).astype(np.float32)
    return table, queries


# --------------------------------------------------------------------------- #
# host-side quantization math (no device involved)
# --------------------------------------------------------------------------- #
@pytest.mark.core
def test_roundtrip_error_bounded_by_half_scale(catalog):
    table, _ = catalog
    quantized = quantize_embeddings(table)
    stats = quantization_error(table, quantized)
    # round-to-nearest on a symmetric grid: per-element error <= scale/2
    assert stats["max_error_to_bound"] <= 1.0 + 1e-6, stats
    assert stats["rel_frobenius_error"] < 0.01, stats
    # int8 values + f32 scales: (E + 4) / 4E of the f32 table -> ~0.27 at E=64
    assert stats["bytes_ratio"] <= (DIM + 4) / (4 * DIM) + 1e-9, stats


@pytest.mark.core
def test_zero_rows_quantize_to_exact_zero():
    table = np.zeros((4, 8), np.float32)
    table[1] = 3.0
    quantized = quantize_embeddings(table)
    assert np.array_equal(quantized.dequantize()[0], np.zeros(8))
    assert quantized.scales[0] == 0.0
    np.testing.assert_allclose(quantized.dequantize()[1], table[1], atol=3.0 / 254)


@pytest.mark.core
def test_quantize_rejects_bad_inputs():
    with pytest.raises(ValueError, match="bits"):
        quantize_embeddings(np.zeros((2, 2), np.float32), bits=4)
    with pytest.raises(ValueError, match="shape"):
        quantize_embeddings(np.zeros(8, np.float32))


# --------------------------------------------------------------------------- #
# device search / pipeline
# --------------------------------------------------------------------------- #
def _recall(reference_ids: np.ndarray, candidate_ids: np.ndarray) -> float:
    k = reference_ids.shape[1]
    return float(
        np.mean(
            [
                len(set(a.tolist()) & set(b.tolist())) / k
                for a, b in zip(reference_ids, candidate_ids)
            ]
        )
    )


@pytest.mark.jax
@pytest.mark.smoke
def test_int8_search_recall_and_bytes(catalog):
    """The acceptance gate: recall@100 ≥ 0.99 vs f32 MIPS, payload ≈ ¼.
    Leaves the CI precision_smoke quant artifact."""
    from replay_tpu.models.ann import MIPSIndex

    table, queries = catalog
    f32_index = MIPSIndex(table)
    int8_index = MIPSIndex(table, precision="int8")
    _, f32_ids = f32_index.search(queries, 100)
    _, int8_ids = int8_index.search(queries, 100)
    recall = _recall(f32_ids, int8_ids)
    table_bytes = int8_index.table_bytes()
    assert recall >= 0.99, recall
    assert table_bytes["bytes_ratio"] <= (DIM + 4) / (4 * DIM) + 1e-9, table_bytes
    assert table_bytes["payload_bytes"] == NUM_ITEMS * DIM + NUM_ITEMS * 4

    base = os.environ.get("REPLAY_TPU_RUN_DIR")
    if base:  # CI artifact: the int8 retrieval gate numbers, re-runnable
        run_dir = os.path.join(base, "precision_smoke")
        os.makedirs(run_dir, exist_ok=True)
        with open(os.path.join(run_dir, "quant_gate.json"), "w") as fh:
            json.dump(
                {
                    "recall_at_100": recall,
                    "bytes_ratio": table_bytes["bytes_ratio"],
                    "catalog": NUM_ITEMS,
                    "dim": DIM,
                    "queries": QUERIES,
                },
                fh,
                indent=1,
            )


@pytest.mark.jax
@pytest.mark.smoke
def test_pipeline_topk_matches_f32_via_exact_rescore(catalog):
    """The re-ranked int8 pipeline's top-k must match the f32 pipeline's on
    the same candidates: the rescore stage scores candidates at exact f32, so
    whenever the quantized sweep surfaces the f32 winners the final cut is
    IDENTICAL — quantization error selects candidates, never ranks them."""
    from replay_tpu.models.ann import MIPSIndex
    from replay_tpu.serve import CandidatePipeline

    table, queries = catalog
    # exercise the re-rank math without SATURATING the sigmoid: saturated
    # scores collapse to exact 1.0 ties and top_k tie-breaks by candidate
    # position, which legitimately differs between the two sweeps
    weights = np.asarray([0.05, 0.1], np.float32)
    f32_pipe = CandidatePipeline(
        MIPSIndex(table), num_candidates=100, top_k=10, reranker_weights=weights
    )
    int8_pipe = CandidatePipeline(
        MIPSIndex(table, precision="int8"),
        num_candidates=100, top_k=10, reranker_weights=weights,
    )
    f32_scores, f32_topk = f32_pipe.rank(queries)
    int8_scores, int8_topk = int8_pipe.rank(queries)

    _, f32_cands = f32_pipe.index.search(queries, 100)
    _, int8_cands = int8_pipe.index.search(queries, 100)
    exact_rows = 0
    for row in range(queries.shape[0]):
        if set(f32_topk[row].tolist()) <= set(int8_cands[row].tolist()):
            # the f32 winners were all retrieved: the exact rescore must
            # reproduce the f32 pipeline's cut — same item SET and same
            # scores (id ORDER may differ only under float tie-breaking: the
            # gathered-rows einsum associates f32 adds differently than the
            # full-table matmul, and the sigmoid saturates near-ties)
            assert set(f32_topk[row].tolist()) == set(int8_topk[row].tolist())
            np.testing.assert_allclose(
                np.sort(f32_scores[row]), np.sort(int8_scores[row]),
                rtol=1e-5, atol=1e-6,
            )
            exact_rows += 1
    # with recall >= 0.99 nearly every row qualifies — the exact-match branch
    # must be the overwhelmingly common case, not a vacuous assertion
    assert exact_rows >= int(0.9 * queries.shape[0]), exact_rows
    # overall agreement even counting the non-qualifying rows
    assert _recall(f32_topk, int8_topk) >= 0.99


@pytest.mark.jax
def test_sharded_int8_matches_unsharded(catalog):
    """The CEFusedTP [I/n, E] row-shard layout reuse: a mesh-sharded int8
    index (non-divisible catalog -> zero-padded tail shard) returns the same
    ids/scores as the unsharded int8 search."""
    from replay_tpu.models.ann import MIPSIndex
    from replay_tpu.nn import make_mesh

    table, queries = catalog
    odd = table[:1999]  # 1999 rows over 8 shards: padding exercised
    unsharded = MIPSIndex(odd, precision="int8")
    sharded = MIPSIndex(odd, mesh=make_mesh(), axis_name="data", precision="int8")
    values_u, ids_u = unsharded.search(queries, 64)
    values_s, ids_s = sharded.search(queries, 64)
    np.testing.assert_allclose(values_s, values_u, rtol=1e-5, atol=1e-6)
    assert np.array_equal(ids_s, ids_u)


@pytest.mark.jax
def test_exact_rescore_reproduces_f32_scores(catalog):
    from replay_tpu.models.ann import MIPSIndex

    table, queries = catalog
    f32_index = MIPSIndex(table)
    int8_index = MIPSIndex(table, precision="int8")
    values, ids = f32_index.search(queries, 50)
    rescored = np.asarray(int8_index.exact_rescore(queries, ids))
    np.testing.assert_allclose(rescored, values, rtol=1e-5, atol=1e-6)
    # the f32 index rescoring its own candidates is the identity check
    own = np.asarray(f32_index.exact_rescore(queries, ids))
    np.testing.assert_allclose(own, values, rtol=1e-5, atol=1e-6)


@pytest.mark.jax
def test_pipeline_spans_mark_the_rescore_stage(catalog):
    """The int8 pipeline traces retrieve → rescore → rerank; the f32 pipeline
    must NOT grow a rescore stage (its scores are already exact)."""
    from replay_tpu.models.ann import MIPSIndex
    from replay_tpu.obs import Tracer
    from replay_tpu.serve import CandidatePipeline

    table, queries = catalog
    for precision, expect_rescore in (("f32", False), ("int8", True)):
        tracer = Tracer()
        pipeline = CandidatePipeline(
            MIPSIndex(table, precision=precision), num_candidates=20, top_k=5
        )
        pipeline.rank(queries[:8], tracer=tracer)
        names = set(tracer.summary())
        assert "retrieve" in names and "rerank" in names
        assert ("rescore" in names) == expect_rescore, (precision, names)
        assert pipeline.stats()["index_precision"] == precision


@pytest.mark.jax
def test_mips_rejects_unknown_precision(catalog):
    from replay_tpu.models.ann import MIPSIndex

    table, _ = catalog
    with pytest.raises(ValueError, match="precision"):
        MIPSIndex(table, precision="int4")


@pytest.mark.core
def test_quantized_table_shape_accessors():
    quantized = quantize_embeddings(np.ones((6, 4), np.float32))
    assert isinstance(quantized, QuantizedTable)
    assert (quantized.num_items, quantized.dim) == (6, 4)
    assert quantized.nbytes == 6 * 4 + 6 * 4  # int8 values + f32 scales
