"""The socket-boundary fleet: real replica PROCESSES behind real HTTP.

Every other serve test crosses at most a thread boundary. Here each
``ScoringService`` runs in its own OS process behind ``ReplicaServer``
(spawned via the portfile handshake — port 0, nothing hardcoded), the fleet
router drives :class:`~replay_tpu.serve.RemoteReplica` clients through the
SAME duck-typed surface, health comes off a pure ``/healthz`` scrape, and
chaos is a true ``SIGKILL`` of a server process — no atexit, no close path,
just a dead socket. The claims: taxonomy refusals survive the wire with
their hints, a killed replica's traffic fails over with zero hung futures,
heartbeat misses declare it dead, and a respawned server (fresh ephemeral
port) is picked up without rebuilding the fleet.
"""

import signal
import time
from pathlib import Path

import numpy as np
import pytest

from replay_tpu.parallel import clean_cpu_env
from replay_tpu.serve import (
    RemoteReplica,
    ReplicaServerProcess,
    ServeError,
    ServiceClosed,
    ServingFleet,
)
from replay_tpu.serve.request import SERVED_FROM
from replay_tpu.utils import KillAtStep

# spawns real jax server processes (engine compiles at startup): jax tier,
# not smoke — the CI multiproc_smoke job runs this file explicitly
pytestmark = pytest.mark.jax

REPO_ROOT = Path(__file__).resolve().parents[2]
NUM_ITEMS = 32
SEQ_LEN = 8
REPLICAS = 3


@pytest.fixture(scope="module")
def servers():
    env = clean_cpu_env(local_devices=1, repo_root=REPO_ROOT)
    procs = [
        ReplicaServerProcess(
            env=env,
            args=[
                "--num-items", str(NUM_ITEMS),
                "--seq-len", str(SEQ_LEN),
                "--embedding-dim", "8",
                "--num-blocks", "1",
            ],
        )
        for _ in range(REPLICAS)
    ]
    try:
        for proc in procs:  # concurrent startup: the compiles overlap
            proc.spawn(wait=False)
        for proc in procs:
            proc.wait_ready()
        yield procs
    finally:
        for proc in procs:
            proc.terminate()


def _history_for(user: int):
    rng = np.random.default_rng(1000 + user)
    return rng.integers(0, NUM_ITEMS, size=int(rng.integers(3, SEQ_LEN))).tolist()


class TestRemoteReplica:
    def test_score_roundtrip_over_the_socket(self, servers):
        replica = RemoteReplica(servers[0]).start()
        try:
            cold = replica.score(1, history=_history_for(1), timeout=60)
            assert cold.scores.shape == (NUM_ITEMS,)
            assert cold.served_from in SERVED_FROM
            assert np.isfinite(cold.scores).all()
            # second touch: the SERVER-side cache answered (state lives in
            # the replica process, not the client)
            hit = replica.score(1, timeout=60)
            assert hit.served_from == "hit"
            np.testing.assert_array_equal(hit.scores, cold.scores)
        finally:
            replica.close()

    def test_heartbeat_is_a_pure_scrape(self, servers):
        replica = RemoteReplica(servers[0]).start()
        try:
            heartbeat = replica.heartbeat()
            assert heartbeat["live"] is True
            # the gauges the fleet monitor windows: all off the wire
            for key in ("queued", "max_depth", "breaker_state", "requests", "errors"):
                assert key in heartbeat
            stats = replica.stats()
            assert stats["requests"] >= 0
            assert stats["mode"] == "full"
        finally:
            replica.close()

    def test_taxonomy_refusals_survive_the_wire(self, servers):
        replica = RemoteReplica(servers[0]).start()
        try:
            # an interaction that cannot land on a cold cache refuses with
            # the re-anchor KeyError — 404 on the wire, KeyError again here
            with pytest.raises(KeyError, match="history="):
                replica.score(987654, new_items=[3], timeout=60)
        finally:
            replica.close()

    def test_transport_death_is_service_closed(self):
        # nothing listens here: connection refused must surface as the
        # retryable ServiceClosed, and heartbeat must raise (a monitor miss)
        ghost = RemoteReplica("http://127.0.0.1:1").start()
        try:
            with pytest.raises(ServiceClosed, match="unreachable"):
                ghost.score(1, timeout=5)
            with pytest.raises(Exception):
                ghost.heartbeat()
        finally:
            ghost.close()

    def test_closed_client_fails_fast(self, servers):
        replica = RemoteReplica(servers[0]).start()
        replica.close()
        with pytest.raises(ServiceClosed):
            replica.submit(1).result(timeout=5)


@pytest.fixture(scope="module")
def blackbox_servers(tmp_path_factory):
    """Two replica processes, each with a flight ring and a live exporter on
    an ephemeral port (published through ``<portfile>.metrics``)."""
    base = tmp_path_factory.mktemp("blackbox_fleet")
    env = clean_cpu_env(local_devices=1, repo_root=REPO_ROOT)
    procs = [
        ReplicaServerProcess(
            env=env,
            args=[
                "--num-items", str(NUM_ITEMS),
                "--seq-len", str(SEQ_LEN),
                "--embedding-dim", "8",
                "--num-blocks", "1",
            ],
            flight_path=str(base / f"flight.s{i}.ring"),
            metrics_port=0,
        )
        for i in range(2)
    ]
    try:
        for proc in procs:
            proc.spawn(wait=False)
        for proc in procs:
            proc.wait_ready()
        yield procs
    finally:
        for proc in procs:
            proc.terminate()


class TestBlackboxAndFederation:
    def test_federated_metrics_over_two_real_processes(self, blackbox_servers):
        """The acceptance claim: one federated registry over two real OS
        processes — counters equal the sum EXACTLY (reconciled against each
        service's own ``stats()``), histograms bucket-merge losslessly,
        gauges carry per-process labels."""
        from replay_tpu.obs.federate import federate_snapshots, scrape_snapshot

        replicas = [RemoteReplica(proc).start() for proc in blackbox_servers]
        try:
            for index, replica in enumerate(replicas):
                for user in range(5 + index * 2):  # 5 and 7: unequal on purpose
                    replica.score(
                        10_000 * (index + 1) + user,
                        history=_history_for(user), timeout=60,
                    )
            stats = [replica.stats() for replica in replicas]
            snapshots = [
                scrape_snapshot(proc.metrics_url) for proc in blackbox_servers
            ]
            merged = federate_snapshots(snapshots).snapshot()

            # counters: federated total == exact sum of per-member counters
            # == the services' own request accounting
            member_rows = [
                s["replay_serve_rows_total"]["value"] for s in snapshots
            ]
            assert merged["replay_serve_rows_total"]["value"] == sum(member_rows)
            assert sum(member_rows) == stats[0]["requests"] + stats[1]["requests"]

            # histograms: bucket-merged losslessly across the processes
            fills = [s["replay_serve_batch_fill"] for s in snapshots]
            federated_fill = merged["replay_serve_batch_fill"]
            assert federated_fill["count"] == sum(f["count"] for f in fills)
            assert federated_fill["sum"] == pytest.approx(
                sum(f["sum"] for f in fills)
            )
            for bound in fills[0]["buckets"]:
                assert federated_fill["buckets"][bound] == sum(
                    f["buckets"][bound] for f in fills
                )

            # gauges: one labeled series per process, labeled by the identity
            # block each exporter published (REPLAY_TPU_PROCESS_ID defaults)
            processes = {
                str(s["__identity__"]["process_index"]) for s in snapshots
            }
            for process in processes:
                assert f'replay_serve_up{{process="{process}"}}' in merged
        finally:
            for replica in replicas:
                replica.close()

    def test_sigkilled_server_leaves_a_readable_flight_ring(self, blackbox_servers):
        """kill -9 a replica server mid-service: its flight ring must read
        back with the serve events recorded before death — no exception, no
        corrupt records — and a respawn resumes the SAME ring after the dead
        incarnation's last seqno."""
        from replay_tpu.obs.blackbox import read_flight

        victim = blackbox_servers[1]
        replica = RemoteReplica(victim).start()
        try:
            for user in range(6):
                replica.score(user, history=_history_for(user), timeout=60)
        finally:
            replica.close()

        KillAtStep(pid=victim.pid).fire()
        assert victim.proc.wait(timeout=10) == -signal.SIGKILL

        log = read_flight(victim.flight_path)
        dead_seqno = log.last_seqno
        assert log.recovered > 0
        events = [r["event"] for r in log.records]
        assert events[0] == "flight_open"
        assert "on_serve_start" in events
        assert "on_serve_batch" in events
        # the ring never reaches on_serve_end: SIGKILL means no close path
        assert "on_serve_end" not in events

        # revival reopens the same ring and continues AFTER the corpse's
        # records — the respawned incarnation appends, never clobbers
        victim.respawn()
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            resumed = read_flight(victim.flight_path)
            if resumed.last_seqno > dead_seqno:
                break
            time.sleep(0.2)
        assert resumed.last_seqno > dead_seqno
        assert resumed.records[: len(log.records)] == log.records


class TestSocketFleetChaos:
    def test_fleet_survives_a_sigkilled_replica(self, servers):
        replicas = {f"r{i}": RemoteReplica(proc) for i, proc in enumerate(servers)}
        fleet = ServingFleet(
            replicas,
            hedge_ms=0,  # failover via retry only: deterministic accounting
            heartbeat_interval_s=None,  # poll() driven — no wall-clock races
            heartbeat_misses=2,
        )
        victim = "r1"
        with fleet:
            fleet.poll()
            assert set(fleet.health().values()) == {"healthy"}

            # seed users across the ring; remember one homed on the victim
            users = list(range(40))
            for user in users:
                response = fleet.score(user, history=_history_for(user), timeout=60)
                assert response.replica in replicas
            probe = next(u for u in users if fleet.ring.route(u) == victim)

            # the hard kill: no handler, no close path, a dead socket
            KillAtStep(pid=servers[1].pid).fire()
            assert servers[1].proc.wait(timeout=10) == -signal.SIGKILL

            # an idempotent request homed on the corpse: its ServiceClosed
            # refusal is retried downstream — bounded failover gap, answered
            kill_at = time.monotonic()
            rerouted = fleet.score(probe, timeout=30)
            gap_s = time.monotonic() - kill_at
            assert rerouted.replica != victim
            assert gap_s < 30.0

            # heartbeat scrapes now fail: two polls declare it dead
            fleet.poll()
            fleet.poll()
            assert fleet.health()[victim] == "dead"

            # zero hung requests under post-kill traffic; failures (if any)
            # are taxonomy refusals, never raw transport garbage
            futures = [fleet.submit(user) for user in users]
            deadline = time.monotonic() + 60.0
            for future in futures:
                remaining = max(deadline - time.monotonic(), 0.1)
                try:
                    answer = future.result(timeout=remaining)
                    assert answer.replica != victim
                except (ServeError, KeyError):
                    pass  # the documented refusal taxonomy
            assert all(future.done() for future in futures)

            # revival on a FRESH ephemeral port: the RemoteReplica follows
            # the process object's portfile — no fleet rebuild
            old_address = replicas[victim].address
            servers[1].respawn()
            assert replicas[victim].address != old_address
            fleet.poll()
            assert fleet.health()[victim] == "healthy"

            # the probe user's state died with the process: its home answers
            # again, riding the cold-miss fallback rung rather than erroring
            revived = fleet.score(probe, timeout=30)
            assert revived.replica == victim
            assert revived.served_by == "fallback"
