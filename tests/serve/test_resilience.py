"""MicroBatcher resilience + FallbackScorer (host-only, core tier).

Admission control (bounded lanes → RequestShed), worker supervision
(crash → restart → give-up budget), and the no-orphaned-waiters contract:
a submitted item never outlives ``stop()`` unresolved — the batcher-level
regression tests for the serve-side orphaned-waiter bugs.
"""

import threading
import time

import numpy as np
import pytest

from replay_tpu.serve import (
    FallbackScorer,
    MicroBatcher,
    RequestShed,
    ServiceClosed,
)


class Wedge:
    """A dispatch that blocks until released — the wedged-worker scenario."""

    def __init__(self):
        self.release = threading.Event()
        self.entered = threading.Event()
        self.batches = []

    def __call__(self, lane, items):
        self.batches.append((lane, list(items)))
        self.entered.set()
        self.release.wait(timeout=30.0)


class TestAdmissionControl:
    def test_submit_beyond_max_depth_sheds(self):
        wedge = Wedge()
        batcher = MicroBatcher(wedge, capacity=1, max_wait=0.001, max_depth=2).start()
        try:
            batcher.submit("a", "inflight")
            assert wedge.entered.wait(timeout=5.0)  # worker wedged mid-dispatch
            batcher.submit("a", "q1")
            batcher.submit("a", "q2")  # queue now at max_depth
            with pytest.raises(RequestShed) as info:
                batcher.submit("a", "over")
            assert info.value.lane == "a"
            assert info.value.depth == 2
            assert info.value.max_depth == 2
            assert info.value.retry_after_s is not None
            assert info.value.retry_after_s >= 0.0
            assert batcher.stats()["shed"] == 1
            # other lanes have their own bound — not collaterally shed
            batcher.submit("b", "fine")
        finally:
            wedge.release.set()
            batcher.stop()

    def test_unbounded_by_default(self):
        wedge = Wedge()
        batcher = MicroBatcher(wedge, capacity=1, max_wait=0.001).start()
        try:
            batcher.submit("a", "inflight")
            assert wedge.entered.wait(timeout=5.0)
            for i in range(100):  # the pre-resilience behavior, explicitly kept
                batcher.submit("a", i)
            assert batcher.stats()["shed"] == 0
        finally:
            wedge.release.set()
            batcher.stop()

    def test_shed_happens_before_enqueue(self):
        """A refused submit leaves no dangling state: depth is unchanged."""
        wedge = Wedge()
        batcher = MicroBatcher(wedge, capacity=1, max_wait=0.001, max_depth=1).start()
        try:
            batcher.submit("a", "inflight")
            assert wedge.entered.wait(timeout=5.0)
            batcher.submit("a", "queued")
            for _ in range(3):
                with pytest.raises(RequestShed):
                    batcher.submit("a", "over")
            assert batcher.queued_depth("a") == 1
        finally:
            wedge.release.set()
            batcher.stop()


class TestNoOrphanedWaiters:
    def test_stop_fails_pending_when_worker_is_wedged(self):
        """The orphaned-waiter regression: a wedged dispatch must not let
        stop() hang or strand queued + in-flight items unresolved."""
        wedge = Wedge()
        failed = []
        batcher = MicroBatcher(
            wedge,
            capacity=1,
            max_wait=0.001,
            on_error=lambda lane, items, exc: failed.append((list(items), exc)),
        ).start()
        batcher.submit("a", "inflight")
        assert wedge.entered.wait(timeout=5.0)
        batcher.submit("a", "queued1")
        batcher.submit("a", "queued2")
        start = time.perf_counter()
        batcher.stop(timeout=0.2)  # far below the wedge's 30s
        assert time.perf_counter() - start < 5.0
        resolved = [item for items, _ in failed for item in items]
        assert sorted(resolved) == ["inflight", "queued1", "queued2"]
        assert all(isinstance(exc, ServiceClosed) for _, exc in failed)
        wedge.release.set()  # let the daemon thread die

    def test_stop_resolves_items_whose_dispatch_raises(self):
        failed = []

        def explode(lane, items):
            raise RuntimeError("boom")

        batcher = MicroBatcher(
            explode,
            capacity=8,
            max_wait=60.0,  # stop() must not wait for the deadline
            on_error=lambda lane, items, exc: failed.append((list(items), exc)),
        ).start()
        for i in range(5):
            batcher.submit("a", i)
        batcher.stop()
        assert sorted(item for items, _ in failed for item in items) == list(range(5))

    def test_restart_after_wedged_stop_never_runs_two_workers(self):
        """stop() timing out on a wedged dispatch must not let a later
        start() spawn a second dispatcher beside the still-alive thread —
        the single-worker (single device caller) invariant."""
        wedge = Wedge()
        failed = []
        batcher = MicroBatcher(
            wedge,
            capacity=1,
            max_wait=0.001,
            on_error=lambda lane, items, exc: failed.append(list(items)),
        ).start()
        batcher.submit("a", "inflight")
        assert wedge.entered.wait(timeout=5.0)
        batcher.stop(timeout=0.1)  # the worker is still inside the wedge
        batcher.start()
        workers = [
            t for t in threading.enumerate()
            if t.name == "serve-microbatcher" and t.is_alive()
        ]
        assert len(workers) == 1, f"{len(workers)} dispatcher threads alive"
        batcher.submit("a", "after-restart")
        wedge.release.set()  # the original worker resumes and serves on
        deadline = time.perf_counter() + 5.0
        while time.perf_counter() < deadline:
            if any("after-restart" in items for _, items in wedge.batches):
                break
            time.sleep(0.01)
        assert any("after-restart" in items for _, items in wedge.batches)
        batcher.stop()

    def test_submit_after_stop_raises_service_closed(self):
        batcher = MicroBatcher(lambda lane, items: None, capacity=2).start()
        batcher.stop()
        with pytest.raises(ServiceClosed, match="not running"):
            batcher.submit("a", 1)


class TestWorkerSupervision:
    def test_on_error_raising_crashes_and_restarts_the_worker(self):
        dispatched = []
        on_error_calls = []

        def dispatch(lane, items):
            dispatched.append(list(items))
            if len(dispatched) == 1:
                raise RuntimeError("engine down")

        def on_error(lane, items, exc):
            on_error_calls.append((list(items), exc))
            if len(on_error_calls) == 1:
                raise RuntimeError("resolution failed too")  # crashes the worker

        batcher = MicroBatcher(
            dispatch, capacity=1, max_wait=0.001, on_error=on_error
        ).start()
        try:
            batcher.submit("a", "crasher")
            deadline = time.perf_counter() + 5.0
            while batcher.stats()["worker_crashes"] < 1 and time.perf_counter() < deadline:
                time.sleep(0.01)
            assert batcher.stats()["worker_crashes"] == 1
            # the crashed batch was re-routed through on_error by the supervisor
            assert [items for items, _ in on_error_calls] == [["crasher"], ["crasher"]]
            batcher.submit("a", "survivor")  # the restarted worker serves on
            deadline = time.perf_counter() + 5.0
            while ["survivor"] not in dispatched and time.perf_counter() < deadline:
                time.sleep(0.01)
            assert ["survivor"] in dispatched
        finally:
            batcher.stop()

    def test_exhausted_restart_budget_fails_pending_and_refuses_new_work(self):
        failed = []

        class Hardware(BaseException):
            """Non-Exception: escapes dispatch straight to the supervisor."""

        def dispatch(lane, items):
            raise Hardware()

        batcher = MicroBatcher(
            dispatch,
            capacity=1,
            max_wait=0.001,
            on_error=lambda lane, items, exc: failed.append((list(items), exc)),
            max_worker_restarts=1,
        ).start()
        batcher.submit("a", "first")
        deadline = time.perf_counter() + 5.0
        while time.perf_counter() < deadline:
            try:
                batcher.submit("a", "feed")  # keep the crash loop fed
            except ServiceClosed:
                break
            time.sleep(0.005)
        with pytest.raises(ServiceClosed):
            batcher.submit("a", "after-give-up")
        assert batcher.stats()["worker_crashes"] == 2  # initial + 1 restart
        # everything submitted before the give-up resolved through on_error
        assert failed, "no items were failed"
        batcher.stop()  # idempotent after the give-up


class TestFallbackScorer:
    def test_ranking_is_stable_descending_with_id_tiebreak(self):
        scorer = FallbackScorer([1.0, 5.0, 5.0, 0.0])
        np.testing.assert_array_equal(scorer.ranking, [1, 2, 0, 3])

    def test_top_k(self):
        scorer = FallbackScorer([0.0, 10.0, 3.0, 7.0])
        scores, ids = scorer.score(k=2)
        np.testing.assert_array_equal(ids, [1, 3])
        np.testing.assert_array_equal(scores, [10.0, 7.0])

    def test_candidate_gather(self):
        scorer = FallbackScorer([0.0, 10.0, 3.0, 7.0])
        scores, ids = scorer.score(candidates=[3, 0])
        np.testing.assert_array_equal(ids, [3, 0])
        np.testing.assert_array_equal(scores, [7.0, 0.0])

    def test_full_vector_mode(self):
        scorer = FallbackScorer([2.0, 1.0])
        scores, ids = scorer.score()
        assert ids is None
        np.testing.assert_array_equal(scores, [2.0, 1.0])

    def test_from_interactions_counts(self):
        scorer = FallbackScorer.from_interactions([1, 1, 2, 1, 3], num_items=5)
        np.testing.assert_array_equal(scorer.item_scores, [0, 3, 1, 1, 0])
        _, ids = scorer.score(k=1)
        assert ids[0] == 1

    def test_rejects_empty_and_2d(self):
        with pytest.raises(ValueError):
            FallbackScorer([])
        with pytest.raises(ValueError):
            FallbackScorer(np.ones((2, 2)))
