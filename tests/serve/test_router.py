"""Host-only fleet routing core: the hash ring, health machine, backoff,
hedging, failover and drain — no jax, no device, fake replicas.

The fleet (``serve/fleet.py``) is duck-typed over its replicas exactly so
this tier exists: every routing decision, retry, hedge race and drain
handshake is exercised against an in-process fake with controllable latency,
shedding and liveness — the micro-batcher/breaker testing strategy applied
one level up.
"""

import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from replay_tpu.obs import TrainerEvent
from replay_tpu.serve import (
    BackoffPolicy,
    HashRing,
    NoHealthyReplica,
    ReplicaHealth,
    RequestShed,
    ServingFleet,
)
from replay_tpu.serve.request import ScoreResponse

pytestmark = pytest.mark.core


class EventLog:
    def __init__(self):
        self.events = []
        self._lock = threading.Lock()

    def log_event(self, event: TrainerEvent) -> None:
        with self._lock:
            self.events.append((event.event, dict(event.payload)))

    def named(self, name):
        with self._lock:
            return [payload for event, payload in self.events if event == name]


class FakeBatcher:
    def __init__(self):
        self.live = True
        self.pending = 0

    @property
    def idle(self):
        return self.pending == 0

    def queued_depth(self, lane=None):
        return self.pending


class FakeService:
    """A controllable ScoringService stand-in: resolves (optionally delayed),
    sheds the first N submits, and flips liveness for heartbeat tests."""

    def __init__(self, name, delay_s=0.0, shed_first=0, retry_after_s=0.02):
        self.name = name
        self.delay_s = delay_s
        self.shed_remaining = shed_first
        self.retry_after_s = retry_after_s
        self.alive = True
        self.submits = 0
        self.submitted_kwargs = []
        self.futures = []
        self.batcher = FakeBatcher()
        self.published = []
        self.promoted = []
        self.closed = False

    def start(self):
        return self

    def close(self):
        self.closed = True
        self.alive = False

    def heartbeat(self):
        if not self.alive:
            raise RuntimeError(f"{self.name} is down")
        return {
            "live": True,
            "queued": self.batcher.pending,
            "max_depth": 16,
            "breaker_state": "closed",
            "requests": self.submits,
            "errors": 0,
        }

    def stats(self):
        return {"submits": self.submits}

    def publish_candidate(self, params, label="", pipeline=None):
        self.published.append(label)
        return len(self.published)

    def promote(self, generation):
        self.promoted.append(generation)
        return {"to_generation": generation}

    def close_fails_pending(self):
        """The real service's close() contract: pending futures resolve."""
        from replay_tpu.serve import ServiceClosed

        self.close()
        for future in self.futures:
            if not future.done():
                future.set_exception(ServiceClosed())

    def submit(self, user_id, **kwargs):
        self.submits += 1
        self.submitted_kwargs.append(kwargs)
        future = Future()
        self.futures.append(future)
        if self.shed_remaining > 0:
            self.shed_remaining -= 1
            future.set_exception(
                RequestShed(("encode", 1), 16, 16, retry_after_s=self.retry_after_s)
            )
            return future

        def resolve():
            if future.set_running_or_notify_cancel():
                future.set_result(
                    ScoreResponse(
                        user_id=user_id,
                        scores=np.zeros(3),
                        item_ids=None,
                        served_from="hit",
                        lane="hit",
                        queue_wait_s=0.0,
                    )
                )

        if self.delay_s:
            timer = threading.Timer(self.delay_s, resolve)
            timer.daemon = True
            timer.start()
        else:
            resolve()
        return future


def _fleet(services, **kwargs):
    kwargs.setdefault("heartbeat_interval_s", None)  # poll() driven
    kwargs.setdefault("hedge_ms", 0)  # hedging off unless the test wants it
    return ServingFleet(services, **kwargs)


# --------------------------------------------------------------------------- #
# the hash ring
# --------------------------------------------------------------------------- #
class TestHashRing:
    def test_routing_is_deterministic_and_membership_pure(self):
        ring_a = HashRing(("a", "b", "c"))
        ring_b = HashRing(("c", "a", "b"))  # insertion order must not matter
        for user in range(200):
            assert ring_a.route(user) == ring_b.route(user)
        assert ring_a.preference(7) == ring_b.preference(7)
        assert len(set(ring_a.preference(7))) == 3

    def test_spread_is_roughly_balanced(self):
        ring = HashRing(("a", "b", "c", "d"))
        spread = ring.spread(8000)
        assert set(spread) == {"a", "b", "c", "d"}
        for fraction in spread.values():
            # 64 vnodes keeps the imbalance moderate; the bound is loose on
            # purpose — balance is statistical, stability is exact
            assert 0.1 < fraction < 0.45, spread

    def test_bounded_movement_on_add(self):
        """Adding a 4th replica must remap roughly 1/4 of users — and NEVER
        remap a user between two old replicas (movement only TOWARD the new
        one): the property that keeps every other replica's cache hot."""
        ring = HashRing(("a", "b", "c"))
        before = {user: ring.route(user) for user in range(8000)}
        ring.add("d")
        moved = 0
        for user, home in before.items():
            after = ring.route(user)
            if after != home:
                moved += 1
                assert after == "d", "a user moved between two OLD replicas"
        assert 0.10 < moved / len(before) < 0.40, moved / len(before)

    def test_bounded_movement_on_remove(self):
        """Removing a replica remaps ONLY its own users."""
        ring = HashRing(("a", "b", "c", "d"))
        before = {user: ring.route(user) for user in range(8000)}
        ring.remove("d")
        for user, home in before.items():
            if home != "d":
                assert ring.route(user) == home, "a survivor's user moved"
            else:
                assert ring.route(user) != "d"

    def test_add_remove_round_trip_restores_routing(self):
        ring = HashRing(("a", "b", "c"))
        before = {user: ring.route(user) for user in range(2000)}
        ring.add("d")
        ring.remove("d")
        assert {user: ring.route(user) for user in range(2000)} == before

    def test_empty_ring_raises(self):
        with pytest.raises(LookupError, match="empty"):
            HashRing(()).route(1)


# --------------------------------------------------------------------------- #
# health machine + backoff
# --------------------------------------------------------------------------- #
class TestReplicaHealth:
    def test_legal_lifecycle(self):
        health = ReplicaHealth("r0")
        assert health.takes_traffic and health.takes_failover
        assert health.transition("degraded", "lane_depth")
        assert health.takes_traffic and not health.takes_failover
        assert health.transition("draining", "drain")
        assert not health.takes_traffic
        assert health.transition("healthy", "rejoin")
        assert health.transition("dead", "heartbeat")
        assert health.transition("healthy", "revived")
        assert len(health.transitions) == 5

    def test_illegal_transitions_raise(self):
        health = ReplicaHealth("r0")
        health.transition("dead", "heartbeat")
        with pytest.raises(ValueError, match="illegal"):
            health.transition("degraded", "nope")  # dead -> degraded
        with pytest.raises(ValueError, match="unknown"):
            health.transition("zombie")

    def test_same_state_is_a_noop(self):
        health = ReplicaHealth("r0")
        assert not health.transition("healthy", "again")
        assert health.transitions == []


class TestBackoffPolicy:
    def test_exponential_growth_and_cap(self):
        policy = BackoffPolicy(base_s=0.01, multiplier=2.0, cap_s=0.05, max_retries=3)
        assert policy.delay(0) == pytest.approx(0.01)
        assert policy.delay(1) == pytest.approx(0.02)
        assert policy.delay(10) == pytest.approx(0.05)  # capped
        assert not policy.exhausted(2)
        assert policy.exhausted(3)

    def test_retry_after_hint_is_honored(self):
        """The shed lane's own drain estimate is a FLOOR on the delay: the
        backoff may wait longer, never shorter."""
        policy = BackoffPolicy(base_s=0.001, multiplier=2.0, cap_s=1.0)
        assert policy.delay(0, retry_after_s=0.25) >= 0.25
        # a hint beyond the cap still wins (the lane knows its backlog)
        assert policy.delay(0, retry_after_s=5.0) >= 5.0
        # backoff already past the hint: backoff stands (capped)
        assert policy.delay(12, retry_after_s=0.01) == pytest.approx(1.0)

    def test_hint_beyond_cap_is_exact_not_inflated(self):
        """``retry_after_s > cap_s``: the delay is EXACTLY the hint — the cap
        yields to the lane's drain estimate, but nothing may stretch the wait
        past what the lane itself asked for."""
        policy = BackoffPolicy(base_s=0.01, multiplier=2.0, cap_s=0.05)
        assert policy.delay(0, retry_after_s=0.5) == pytest.approx(0.5)
        # even with the backoff term saturated at the cap, the hint stands
        assert policy.delay(10_000, retry_after_s=0.5) == pytest.approx(0.5)

    def test_extreme_attempts_never_overflow(self):
        """``multiplier**attempt`` past float range (2.0**1024 raises
        OverflowError in raw float math) must come back as the cap, never as
        an exception out of the retry scheduler — and ``exhausted`` must hold
        at any magnitude."""
        policy = BackoffPolicy(base_s=0.01, multiplier=2.0, cap_s=0.05, max_retries=3)
        assert policy.delay(20_000) == pytest.approx(0.05)
        assert policy.delay(2**40) == pytest.approx(0.05)
        assert policy.exhausted(2**40)

    def test_negative_attempt_clamps_to_base(self):
        """A (buggy or wrapped) negative attempt behaves as attempt 0: the
        first delay, not a sub-base or negative wait."""
        policy = BackoffPolicy(base_s=0.01, multiplier=2.0, cap_s=0.05)
        assert policy.delay(-3) == pytest.approx(0.01)


# --------------------------------------------------------------------------- #
# the fleet: routing, failover, hedging, retries, drain
# --------------------------------------------------------------------------- #
class TestFleetRouting:
    def test_routes_to_home_and_stamps_replica(self):
        services = {name: FakeService(name) for name in ("a", "b", "c")}
        with _fleet(services) as fleet:
            for user in range(20):
                response = fleet.score(user, timeout=5)
                assert response.replica == fleet.ring.route(user)
            assert fleet.stats()["reroutes"] == 0
            assert fleet.stats()["answered"] == 20

    def test_no_healthy_replica_fails_fast(self):
        services = {name: FakeService(name) for name in ("a", "b")}
        with _fleet(services, heartbeat_misses=1) as fleet:
            for service in services.values():
                service.alive = False
            fleet.poll()
            future = fleet.submit(1)
            with pytest.raises(NoHealthyReplica):
                future.result(timeout=5)
            assert fleet.stats()["no_healthy_refusals"] == 1


class TestFailover:
    def test_dead_replica_rehomes_its_users(self):
        """Heartbeat death: the victim's users are served by their ring
        successor; other replicas' users stay put (cache locality)."""
        services = {name: FakeService(name) for name in ("a", "b", "c")}
        log = EventLog()
        with _fleet(services, heartbeat_misses=2, logger=log) as fleet:
            victim = fleet.ring.route("victim-user")
            others = {
                user: fleet.ring.route(user)
                for user in range(50)
                if fleet.ring.route(user) != victim
            }
            services[victim].alive = False
            fleet.poll()
            assert fleet.health()[victim] != "dead"  # 1 miss < threshold
            fleet.poll()
            assert fleet.health()[victim] == "dead"
            # the victim's user is served by its preference successor
            response = fleet.score("victim-user", timeout=5)
            expected = [
                rid for rid in fleet.ring.preference("victim-user") if rid != victim
            ][0]
            assert response.replica == expected
            # everyone else stays home
            for user, home in list(others.items())[:10]:
                assert fleet.score(user, timeout=5).replica == home
            stats = fleet.stats()
            assert stats["failovers"] == 1
            assert stats["reroutes"] >= 1
            # one on_failover + the health transition event
            assert len(log.named("on_failover")) == 1
            transitions = log.named("on_replica_health")
            assert any(
                e["replica"] == victim and e["to"] == "dead" for e in transitions
            )

    def test_revived_replica_takes_its_users_back(self):
        services = {name: FakeService(name) for name in ("a", "b", "c")}
        with _fleet(services, heartbeat_misses=1) as fleet:
            victim = fleet.ring.route("victim-user")
            services[victim].alive = False
            fleet.poll()
            assert fleet.score("victim-user", timeout=5).replica != victim
            services[victim].alive = True
            fleet.poll()
            assert fleet.health()[victim] == "healthy"
            assert fleet.score("victim-user", timeout=5).replica == victim

    def test_degraded_breaker_signal_from_heartbeat(self):
        services = {name: FakeService(name) for name in ("a", "b")}
        with _fleet(services) as fleet:
            original = services["a"].heartbeat

            def degraded_heartbeat():
                record = original()
                record["breaker_state"] = "open"
                return record

            services["a"].heartbeat = degraded_heartbeat
            fleet.poll()
            assert fleet.health()["a"] == "degraded"
            # degraded still takes HOME traffic (warm cache beats rerouting)
            user = next(u for u in range(100) if fleet.ring.route(u) == "a")
            assert fleet.score(user, timeout=5).replica == "a"
            services["a"].heartbeat = original
            fleet.poll()
            assert fleet.health()["a"] == "healthy"


class TestHedging:
    def test_hedge_cancels_the_loser_exactly_once(self):
        """A slow primary past the hedge delay races a second replica; the
        fast hedge wins and the slow loser is cancelled exactly once."""
        services = {
            "slow": FakeService("slow", delay_s=0.5),
            "b": FakeService("b"),
            "c": FakeService("c"),
        }
        with _fleet(services, hedge_ms=25) as fleet:
            user = next(u for u in range(200) if fleet.ring.route(u) == "slow")
            started = time.perf_counter()
            response = fleet.score(user, timeout=5)
            elapsed = time.perf_counter() - started
            assert response.replica != "slow"
            assert elapsed < 0.4  # beat the slow primary's 0.5 s
            stats = fleet.stats()
            assert stats["hedges"] == 1
            assert stats["hedge_wins"] == 1
            assert stats["hedge_cancelled"] == 1  # exactly once

    def test_fast_primary_never_hedges(self):
        services = {name: FakeService(name) for name in ("a", "b")}
        with _fleet(services, hedge_ms=50) as fleet:
            for user in range(10):
                fleet.score(user, timeout=5)
            assert fleet.stats()["hedges"] == 0

    def test_non_idempotent_requests_never_hedge(self):
        services = {
            "slow": FakeService("slow", delay_s=0.2),
            "b": FakeService("b"),
        }
        with _fleet(services, hedge_ms=10) as fleet:
            user = next(u for u in range(200) if fleet.ring.route(u) == "slow")
            response = fleet.score(user, new_items=[5], timeout=5)
            assert response.replica == "slow"  # waited for the mutation's home
            assert fleet.stats()["hedges"] == 0


class TestRetryBackoff:
    def test_retry_honors_retry_after_s(self):
        """A shed with a retry-after hint is retried no EARLIER than the
        hint — on the same (only) replica, which then accepts."""
        shedder = FakeService("s", shed_first=1, retry_after_s=0.08)
        with _fleet(
            {"s": shedder}, backoff=BackoffPolicy(base_s=0.001, max_retries=2)
        ) as fleet:
            started = time.perf_counter()
            response = fleet.score(1, timeout=5)
            elapsed = time.perf_counter() - started
            assert response.replica == "s"
            assert elapsed >= 0.08, f"retried before retry_after_s ({elapsed:.3f}s)"
            assert shedder.submits == 2
            assert fleet.stats()["retries"] == 1

    def test_retries_are_capped(self):
        shedder = FakeService("s", shed_first=100, retry_after_s=0.005)
        with _fleet(
            {"s": shedder}, backoff=BackoffPolicy(base_s=0.001, max_retries=2)
        ) as fleet:
            future = fleet.submit(1)
            with pytest.raises(RequestShed):
                future.result(timeout=5)
            assert shedder.submits == 3  # initial + 2 retries
            assert fleet.stats()["retries"] == 2

    def test_non_idempotent_requests_are_never_retried(self):
        """new_items traffic mutates the home cache at submit: re-sending it
        would double-land the interaction, so the shed propagates."""
        shedder = FakeService("s", shed_first=1, retry_after_s=0.01)
        with _fleet(
            {"s": shedder}, backoff=BackoffPolicy(base_s=0.001, max_retries=2)
        ) as fleet:
            future = fleet.submit(1, new_items=[3])
            with pytest.raises(RequestShed):
                future.result(timeout=5)
            assert shedder.submits == 1
            assert fleet.stats()["retries"] == 0

    def test_shed_retry_fails_over_to_another_replica(self):
        services = {
            "a": FakeService("a", shed_first=5, retry_after_s=0.005),
            "b": FakeService("b"),
            "c": FakeService("c"),
        }
        with _fleet(
            services, backoff=BackoffPolicy(base_s=0.001, max_retries=2)
        ) as fleet:
            user = next(u for u in range(200) if fleet.ring.route(u) == "a")
            response = fleet.score(user, timeout=5)
            assert response.replica != "a"
            assert fleet.stats()["reroutes"] >= 1


class TestDrainProtocol:
    def test_drain_waits_for_idle_with_zero_orphans(self):
        """Drain blocks until queued+in-flight work empties; traffic routed
        during the drain goes elsewhere; rejoin restores the replica."""
        services = {name: FakeService(name) for name in ("a", "b", "c")}
        log = EventLog()
        with _fleet(services, logger=log) as fleet:
            services["a"].batcher.pending = 3  # simulated in-flight backlog

            def finish_backlog():
                time.sleep(0.05)
                services["a"].batcher.pending = 0

            worker = threading.Thread(target=finish_backlog, daemon=True)
            worker.start()
            started = time.perf_counter()
            assert fleet.drain("a", timeout_s=5.0)
            assert time.perf_counter() - started >= 0.04
            assert fleet.health()["a"] == "draining"
            # new traffic for a's users goes elsewhere while draining
            user = next(u for u in range(200) if fleet.ring.route(u) == "a")
            assert fleet.score(user, timeout=5).replica != "a"
            fleet.rejoin("a")
            assert fleet.health()["a"] == "healthy"
            assert fleet.score(user, timeout=5).replica == "a"
            transitions = [
                (e["from"], e["to"]) for e in log.named("on_replica_health")
            ]
            assert ("healthy", "draining") in transitions
            assert ("draining", "healthy") in transitions

    def test_stale_health_sweeps_never_override_a_drain(self):
        """The poll-vs-drain race guard: a gauge-driven transition decided on
        a STALE state observation (the operator drained the replica between
        the sweep's read and its write) is dropped — never applied to the
        wrong state, never an illegal-transition crash of the monitor."""
        services = {name: FakeService(name) for name in ("a", "b")}
        with _fleet(services) as fleet:
            handle = fleet.handles["a"]
            services["a"].batcher.pending = 0
            assert fleet.drain("a", timeout_s=1.0)
            # a sweep that observed "healthy" before the drain landed:
            # its degrade verdict must be dropped, not raised on
            fleet._transition(handle, "degraded", "lane_depth", expected="healthy")
            assert fleet.health()["a"] == "draining"
            # and a full poll() against a draining replica (whatever its
            # gauges say) leaves the drain in place
            original = services["a"].heartbeat
            services["a"].heartbeat = lambda: {**original(), "breaker_state": "open"}
            fleet.poll()
            assert fleet.health()["a"] == "draining"

    def test_drain_times_out_on_a_wedged_replica(self):
        services = {name: FakeService(name) for name in ("a", "b")}
        with _fleet(services) as fleet:
            services["a"].batcher.pending = 1  # never drains
            assert not fleet.drain("a", timeout_s=0.05)
            assert fleet.health()["a"] == "draining"

    def test_drain_and_swap_runs_the_promotion_path(self):
        services = {name: FakeService(name) for name in ("a", "b")}
        with _fleet(services) as fleet:
            result = fleet.drain_and_swap("a", params={"w": 1}, label="roll")
            assert result["drained"] and result["replica"] == "a"
            assert services["a"].published == ["roll"]
            assert services["a"].promoted == [1]
            assert fleet.health()["a"] == "healthy"

    def test_rolling_swap_covers_every_replica(self):
        services = {name: FakeService(name) for name in ("a", "b", "c")}
        with _fleet(services) as fleet:
            results = fleet.rolling_swap(params={"w": 1}, label="fleet-roll")
            assert {r["replica"] for r in results} == {"a", "b", "c"}
            for service in services.values():
                assert service.published == ["fleet-roll"]


class TestReviewHardening:
    def test_close_resolves_inflight_clients_not_hangs_them(self):
        """The shutdown-hang regression: a client in flight when close()
        runs must RESOLVE (the replica's ServiceClosed propagates), never
        wait on a retry timer whose scheduler is already gone."""
        from replay_tpu.serve import ServiceClosed

        slow = FakeService("a", delay_s=30.0)  # never resolves on its own
        fleet = _fleet({"a": slow})
        fleet.start()
        client = fleet.submit(1)
        assert not client.done()
        fleet.close()
        slow.close_fails_pending()  # what the real service.close() does
        deadline = time.perf_counter() + 2.0
        while not client.done() and time.perf_counter() < deadline:
            time.sleep(0.01)
        assert client.done(), "close() left an in-flight client hanging"
        with pytest.raises(ServiceClosed):
            client.result(timeout=0)

    def test_revival_does_not_judge_the_death_burst(self):
        """The error-rate window re-anchors on revival: errors accumulated
        while dying must not re-degrade the freshly-healthy replica."""
        services = {name: FakeService(name) for name in ("a", "b")}
        with _fleet(services, heartbeat_misses=1) as fleet:
            counters = {"requests": 100.0, "errors": 0.0, "live": True}

            def heartbeat():
                if not counters["live"]:
                    raise RuntimeError("down")
                return {
                    "live": True, "queued": 0, "max_depth": 16,
                    "breaker_state": "closed",
                    "requests": counters["requests"], "errors": counters["errors"],
                }

            services["a"].heartbeat = heartbeat
            fleet.poll()  # anchor the window at 100 clean requests
            counters["live"] = False
            fleet.poll()
            assert fleet.health()["a"] == "dead"
            # the dying burst: 20 more requests, 18 of them errors
            counters.update(requests=120.0, errors=18.0, live=True)
            fleet.poll()
            assert fleet.health()["a"] == "healthy"
            fleet.poll()  # next sweep judges only the POST-revival window
            assert fleet.health()["a"] == "healthy"

    def test_rolling_swap_skips_dead_replicas(self):
        services = {name: FakeService(name) for name in ("a", "b")}
        with _fleet(services, heartbeat_misses=1) as fleet:
            services["a"].alive = False
            fleet.poll()
            results = fleet.rolling_swap(params={"w": 1}, label="roll")
            by_replica = {r["replica"]: r for r in results}
            assert by_replica["a"].get("skipped") == "dead"
            assert by_replica["b"]["generation"] == 1
            assert services["a"].published == []

    def test_failed_swap_rejoins_the_replica(self):
        """A publish that raises must not strand the replica in draining:
        traffic resumes on the OLD generation and the error surfaces."""
        services = {name: FakeService(name) for name in ("a", "b")}
        with _fleet(services) as fleet:
            def bad_publish(params, label="", pipeline=None):
                raise RuntimeError("candidate rejected")

            services["a"].publish_candidate = bad_publish
            with pytest.raises(RuntimeError, match="candidate rejected"):
                fleet.drain_and_swap("a", params={"w": 1})
            assert fleet.health()["a"] == "healthy"
            assert services["a"].promoted == []

    def test_score_timeout_cancels_the_inner_request(self):
        """A fleet-level client give-up propagates to the replica: the inner
        future is cancelled so the batch builder can skip it."""
        from concurrent.futures import TimeoutError as FutureTimeoutError

        slow = FakeService("a", delay_s=5.0)
        with _fleet({"a": slow}) as fleet:
            with pytest.raises(FutureTimeoutError):
                fleet.score(1, timeout=0.05)
            deadline = time.perf_counter() + 1.0
            while time.perf_counter() < deadline:
                if slow.futures and slow.futures[-1].cancelled():
                    break
                time.sleep(0.01)
            assert slow.futures[-1].cancelled(), "inner request not cancelled"

    def test_concurrent_refusals_schedule_one_retry(self):
        """The attempt-race guard: at most one retry timer per flight, and
        the retry budget is enforced under the flight lock."""
        shedder = FakeService("s", shed_first=10, retry_after_s=0.005)
        with _fleet(
            {"s": shedder}, backoff=BackoffPolicy(base_s=0.001, max_retries=3)
        ) as fleet:
            future = fleet.submit(1)
            with pytest.raises(RequestShed):
                future.result(timeout=5)
            # initial + exactly max_retries submissions, no double-scheduling
            assert shedder.submits == 4
            assert fleet.stats()["retries"] == 3


class TestFleetLifecycle:
    def test_close_closes_every_replica_and_emits_end(self):
        services = {name: FakeService(name) for name in ("a", "b")}
        log = EventLog()
        fleet = _fleet(services, logger=log)
        fleet.start()
        fleet.score(1, timeout=5)
        fleet.close()
        assert all(service.closed for service in services.values())
        ends = log.named("on_fleet_end")
        assert len(ends) == 1 and ends[0]["answered"] == 1
        assert log.named("on_fleet_start")

    def test_monitor_thread_detects_death_in_real_time(self):
        """The one timing-based check: a real monitor thread (tiny interval)
        declares a dead replica without any poll() call."""
        services = {name: FakeService(name) for name in ("a", "b")}
        fleet = ServingFleet(
            services, heartbeat_interval_s=0.01, heartbeat_misses=2, hedge_ms=0
        )
        with fleet:
            services["a"].alive = False
            deadline = time.perf_counter() + 2.0
            while time.perf_counter() < deadline:
                if fleet.health()["a"] == "dead":
                    break
                time.sleep(0.01)
            assert fleet.health()["a"] == "dead"
