"""ScoringService live metrics: the scrapeable endpoint on a running service.

The serving half of the metrics-plane acceptance: ``ScoringService(
metrics_port=0)`` serves qps/fill/queue-wait/shed gauges WHILE answering
traffic, shed totals in the registry reconcile with ``stats()`` after the
throttled events flush at close, and serve-side SLO rules ride the same
watchdog as training.
"""

import json
import math
import time
import urllib.request

import numpy as np
import pytest

import jax

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.nn.sequential.sasrec import SasRec
from replay_tpu.obs import SLORule
from replay_tpu.serve import RequestShed, ScoringService
from replay_tpu.utils.faults import LatencySpike, wrap_method

pytestmark = [pytest.mark.jax, pytest.mark.smoke]

NUM_ITEMS, SEQ_LEN, DIM = 20, 8, 8
HISTORY = [3, 1, 4, 1, 5]


@pytest.fixture(scope="module")
def model_and_params():
    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id", FeatureType.CATEGORICAL, is_seq=True,
            feature_hint=FeatureHint.ITEM_ID, cardinality=NUM_ITEMS, embedding_dim=DIM,
        )
    )
    model = SasRec(
        schema=schema, embedding_dim=DIM, num_blocks=1, max_sequence_length=SEQ_LEN
    )
    ids = np.zeros((2, SEQ_LEN), np.int32)
    params = model.init(
        jax.random.PRNGKey(0), {"item_id": ids}, np.ones((2, SEQ_LEN), bool)
    )["params"]
    return model, params


def _service(model_and_params, **kwargs):
    model, params = model_and_params
    kwargs.setdefault("length_buckets", (SEQ_LEN,))
    kwargs.setdefault("batch_buckets", (1, 4))
    kwargs.setdefault("max_wait_ms", 5.0)
    return ScoringService(model, params, **kwargs)


def _scrape(service, path="/metrics"):
    url = service.metrics_exporter.url
    with urllib.request.urlopen(f"{url}{path}", timeout=10) as response:
        return response.read().decode()


def _gauge(text, name):
    lines = [line for line in text.splitlines() if line.startswith(name + " ")]
    assert lines, f"{name} missing from the scrape"
    return float(lines[0].rsplit(" ", 1)[1])


def test_live_scrape_carries_qps_fill_and_wait(model_and_params):
    service = _service(model_and_params, metrics_port=0)
    with service:
        assert service.metrics_exporter.port is not None
        for i in range(6):
            service.score(f"u{i}", history=HISTORY, timeout=30)
        text = _scrape(service)
        assert _gauge(text, "replay_serve_up") == 1.0
        assert _gauge(text, "replay_serve_rows_total") >= 6
        assert _gauge(text, "replay_serve_qps") > 0
        assert "replay_serve_batch_fill_bucket" in text
        assert "replay_serve_queue_wait_ms_bucket" in text
        snapshot = json.loads(_scrape(service, "/snapshot"))
        fill = snapshot["replay_serve_batch_fill"]
        assert fill["count"] >= 1 and 0.0 < fill["max"] <= 1.0
    # post-close: the endpoint is down, the registry keeps the final gauges
    assert service.metrics_exporter.port is None
    registry = service.metrics_registry
    assert registry.value("replay_serve_up") == 0.0
    assert registry.value("replay_serve_cache_hit_rate") is not None


def test_shed_totals_reconcile_with_stats(model_and_params):
    service = _service(
        model_and_params, metrics_port=0, max_queue_depth=1, max_wait_ms=1.0
    ).start()
    try:
        spike = LatencySpike(at_calls=[0], duration_s=0.5)
        wrap_method(service.engine, "encode", spike)
        blocker = service.submit("blocker", history=HISTORY)
        deadline = time.perf_counter() + 5.0
        while not spike.injected_at and time.perf_counter() < deadline:
            time.sleep(0.005)
        queued = service.submit("queued", history=HISTORY)
        sheds = [service.submit(f"over{i}", history=HISTORY) for i in range(3)]
        for shed in sheds:
            with pytest.raises(RequestShed):
                shed.result(timeout=5)
        blocker.result(timeout=30)
        queued.result(timeout=30)
        stats = service.stats()
        assert stats["shed"] == 3
    finally:
        service.close()
    # close() flushed the throttled on_shed tail, so the registry counter
    # reproduces the service total exactly — the serve_chaos CI contract
    registry = service.metrics_registry
    assert registry.value("replay_serve_shed_total") == stats["shed"]
    assert registry.value("replay_serve_shed_rate") == pytest.approx(
        stats["shed_rate"]
    )
    depth = registry.value(
        "replay_serve_lane_depth", labels={"lane": f"encode:L={SEQ_LEN}"}
    )
    assert depth is not None and depth >= 1


def test_serve_slo_rule_fires_through_the_logger(model_and_params):
    events = []

    class Sink:
        def log_event(self, event):
            events.append(event)

    service = _service(
        model_and_params,
        metrics_port=0,
        logger=Sink(),
        slo_rules=[SLORule("replay_serve_qps", ">", 0.0, name="any_traffic")],
    )
    with service:
        service.score("u", history=HISTORY, timeout=30)
    violations = [e for e in events if e.event == "on_slo_violation"]
    assert [e.payload["rule"] for e in violations] == ["any_traffic"]
    assert service.metrics_registry.value(
        "replay_slo_violations_total", labels={"rule": "any_traffic"}
    ) == 1


def test_busy_port_serves_traffic_unobserved(model_and_params):
    from replay_tpu.obs import MetricsExporter, MetricsRegistry

    squatter = MetricsExporter(MetricsRegistry(), port=0).start()
    try:
        service = _service(model_and_params, metrics_port=squatter.port)
        with service:
            response = service.score("u", history=HISTORY, timeout=30)
            assert math.isfinite(float(np.max(response.scores)))
            assert service.metrics_exporter.port is None
        # the bridge still populated the registry
        assert service.metrics_registry.value("replay_serve_rows_total") >= 1
    finally:
        squatter.close()
