"""ScoringService end-to-end: bitwise parity, cache semantics, fused ranking.

The PR's acceptance gate. Parity contract under test:

* micro-batched scores — any fill level, any (length, batch) bucket — are
  BITWISE identical to a direct AOT ``forward_inference`` call on the same
  right-aligned window at the routed bucket program;
* within one bucket program, scores are bitwise independent of co-riders'
  content and the request's row position (so batching composition never
  matters);
* cache-incremental scores (the advance path) are bitwise identical to the
  direct call on the full updated history;
* pure cache hits are bitwise identical to the split direct reference
  (encode program → hidden, get_logits program → scores) and allclose to the
  fused single-program call (XLA may differ in the last ulp across batch
  shapes — which is why every response carries its ``batch_bucket``).
"""

import json
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.models import MIPSIndex
from replay_tpu.nn.sequential.sasrec import SasRec
from replay_tpu.obs import JsonlLogger, Tracer
from replay_tpu.serve import CandidatePipeline, ScoringService, make_window

pytestmark = [pytest.mark.jax, pytest.mark.smoke]

NUM_ITEMS, SEQ_LEN, DIM = 20, 8, 8


@pytest.fixture(scope="module")
def model_and_params():
    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id", FeatureType.CATEGORICAL, is_seq=True,
            feature_hint=FeatureHint.ITEM_ID, cardinality=NUM_ITEMS, embedding_dim=DIM,
        )
    )
    model = SasRec(
        schema=schema, embedding_dim=DIM, num_blocks=1, max_sequence_length=SEQ_LEN
    )
    ids = np.zeros((2, SEQ_LEN), np.int32)
    params = model.init(
        jax.random.PRNGKey(0), {"item_id": ids}, np.ones((2, SEQ_LEN), bool)
    )["params"]
    return model, params


@pytest.fixture(scope="module")
def direct(model_and_params):
    """AOT forward_inference at a (length, batch-bucket) program — THE direct
    call every serve response must reproduce bit-for-bit."""
    model, params = model_and_params
    programs = {}

    def fwd(params, ids, mask):
        return model.apply(
            {"params": params}, {"item_id": ids}, mask, method=SasRec.forward_inference
        )

    def scores(items, length_bucket, batch_bucket, batch_rows=None):
        key = (length_bucket, batch_bucket)
        if key not in programs:
            programs[key] = (
                jax.jit(fwd)
                .lower(
                    params,
                    jax.ShapeDtypeStruct((batch_bucket, length_bucket), jnp.int32),
                    jax.ShapeDtypeStruct((batch_bucket, length_bucket), jnp.bool_),
                )
                .compile()
            )
        window, mask, _ = make_window(items, length_bucket)
        rows = batch_rows if batch_rows is not None else [(window, mask)] * batch_bucket
        ids = np.stack([r[0] for r in rows])
        masks = np.stack([r[1] for r in rows])
        return np.asarray(programs[key](params, ids, masks))

    return scores


@pytest.fixture()
def service(model_and_params):
    model, params = model_and_params
    svc = ScoringService(
        model, params,
        length_buckets=(4, SEQ_LEN),
        batch_buckets=(1, 4),
        max_wait_ms=30.0,
        tracer=Tracer(),
    )
    with svc:
        yield svc


class TestMicroBatchedParity:
    def test_any_fill_any_bucket_matches_direct_forward_inference(self, service, direct):
        rng = np.random.default_rng(0)
        histories = {
            u: list(rng.integers(0, NUM_ITEMS, rng.integers(1, 14))) for u in range(7)
        }
        futures = {u: service.submit(u, history=h) for u, h in histories.items()}
        for u, future in futures.items():
            response = future.result(timeout=30)
            assert response.served_from == "cold"
            length_bucket = service.engine.route_length(min(len(histories[u]), SEQ_LEN))
            assert response.lane == f"encode:L={length_bucket}"
            want = direct(histories[u], length_bucket, response.batch_bucket)[0]
            np.testing.assert_array_equal(response.scores, want)

    def test_corider_content_and_row_position_never_change_scores(self, service, direct):
        """The same window scored in two different batch compositions (and at
        two row positions) returns bit-identical scores."""
        target = [3, 1, 4, 1, 5, 9, 2, 6]
        first = service.score("t", history=target, timeout=30)
        # different co-riders, target submitted LAST (different row position)
        others = [service.submit(f"o{i}", history=[i + 1] * 8) for i in range(2)]
        second_future = service.submit("t2", history=target)
        for future in others:
            future.result(timeout=30)
        second = second_future.result(timeout=30)
        assert first.batch_bucket == second.batch_bucket or (
            # compositions may land in different buckets; then compare via the
            # direct program, which is the actual contract
            True
        )
        want_first = direct(target, SEQ_LEN, first.batch_bucket)[0]
        want_second = direct(target, SEQ_LEN, second.batch_bucket)[0]
        np.testing.assert_array_equal(first.scores, want_first)
        np.testing.assert_array_equal(second.scores, want_second)
        if first.batch_bucket == second.batch_bucket:
            np.testing.assert_array_equal(first.scores, second.scores)

    def test_top_k_and_candidate_gathers_are_exact(self, service, direct):
        history = [2, 7, 1, 8]
        cold = service.score("k-user", history=history, timeout=30)
        length_bucket = service.engine.route_length(len(history))
        full = direct(history, length_bucket, cold.batch_bucket)[0]

        topk = service.score("k-user", k=3, timeout=30)
        # the hit lane reuses the cached embedding; its scores gather/sort
        # must be internally consistent AND allclose to the cold program
        np.testing.assert_allclose(topk.scores, full[topk.item_ids], rtol=1e-5, atol=1e-6)
        assert set(topk.item_ids) == set(np.argsort(-full, kind="stable")[:3])

        gathered = service.score("k-user", candidates=[0, 5, 9], timeout=30)
        np.testing.assert_array_equal(gathered.item_ids, [0, 5, 9])
        np.testing.assert_allclose(gathered.scores, full[[0, 5, 9]], rtol=1e-5, atol=1e-6)


class TestCacheParity:
    def test_advance_is_bitwise_equal_to_full_reencode(self, service, direct):
        history = [1, 2, 3]
        service.score("adv", history=history, timeout=30)
        response = service.score("adv", new_items=[7, 9], timeout=30)
        assert response.served_from == "advance"
        updated = history + [7, 9]
        length_bucket = service.engine.route_length(len(updated))
        want = direct(updated, length_bucket, response.batch_bucket)[0]
        np.testing.assert_array_equal(response.scores, want)

    def test_advance_slides_past_the_window_capacity(self, service, direct):
        history = list(range(1, 9))  # already fills L=8
        service.score("roll", history=history, timeout=30)
        response = service.score("roll", new_items=[11, 12], timeout=30)
        updated = history + [11, 12]  # window keeps the most recent 8
        want = direct(updated, SEQ_LEN, response.batch_bucket)[0]
        np.testing.assert_array_equal(response.scores, want)

    def test_history_resend_fallback_matches_advance_path(self, service, direct):
        service.score("fb", history=[1, 2], timeout=30)
        advanced = service.score("fb", new_items=[3], timeout=30)
        resent = service.score("fb2", history=[1, 2, 3], timeout=30)
        assert resent.served_from == "cold"
        if advanced.batch_bucket == resent.batch_bucket and advanced.lane == resent.lane:
            np.testing.assert_array_equal(advanced.scores, resent.scores)
        want = direct([1, 2, 3], service.engine.route_length(3), resent.batch_bucket)[0]
        np.testing.assert_array_equal(resent.scores, want)

    def test_pure_hit_skips_the_encoder_and_is_deterministic(
        self, service, model_and_params
    ):
        model, params = model_and_params
        history = [4, 2, 4, 2, 4]
        cold = service.score("hit", history=history, timeout=30)
        encodes_before = service.engine.encode_calls
        hit_a = service.score("hit", timeout=30)
        hit_b = service.score("hit", timeout=30)
        assert service.engine.encode_calls == encodes_before  # no re-encode
        assert hit_a.served_from == "hit" and hit_a.lane == "hit"
        np.testing.assert_array_equal(hit_a.scores, hit_b.scores)
        np.testing.assert_allclose(hit_a.scores, cold.scores, rtol=1e-5, atol=1e-6)

        # the split direct reference: AOT hidden program -> AOT get_logits
        # program at the hit bucket — bitwise
        def body_last(params, ids, mask):
            hidden = model.apply(
                {"params": params}, {"item_id": ids}, mask, method=SasRec.__call__
            )
            return hidden[:, -1, :]

        def score_hidden(params, hidden):
            return model.apply({"params": params}, hidden, method=SasRec.get_logits)

        length_bucket = service.engine.route_length(len(history))
        window, mask, _ = make_window(history, length_bucket)
        encode_program = (
            jax.jit(body_last)
            .lower(
                params,
                jax.ShapeDtypeStruct((cold.batch_bucket, length_bucket), jnp.int32),
                jax.ShapeDtypeStruct((cold.batch_bucket, length_bucket), jnp.bool_),
            )
            .compile()
        )
        hidden = np.asarray(
            encode_program(
                params,
                np.repeat(window[None], cold.batch_bucket, 0),
                np.repeat(mask[None], cold.batch_bucket, 0),
            )
        )[:1]
        score_program = (
            jax.jit(score_hidden)
            .lower(params, jax.ShapeDtypeStruct((hit_a.batch_bucket, DIM), jnp.float32))
            .compile()
        )
        want = np.asarray(
            score_program(params, np.repeat(hidden, hit_a.batch_bucket, 0))
        )[0]
        np.testing.assert_array_equal(hit_a.scores, want)

    def test_unknown_user_without_history_fails_fast(self, service):
        future = service.submit("nobody")
        with pytest.raises(KeyError, match="no cached state"):
            future.result(timeout=10)


class TestRetrievalPipeline:
    @pytest.fixture(scope="class")
    def retrieval_service(self, model_and_params):
        model, params = model_and_params
        item_weights = np.asarray(
            model.apply({"params": params}, method=SasRec.get_item_weights)
        )
        pipeline = CandidatePipeline(
            MIPSIndex(item_weights),
            num_candidates=10,
            top_k=5,
            reranker_weights=np.asarray([1.5, -0.2]),
        )
        svc = ScoringService(
            model, params,
            batch_buckets=(1, 4),
            max_wait_ms=20.0,
            retrieval=pipeline,
            tracer=Tracer(),
        )
        with svc:
            yield svc

    def test_concurrent_clients_get_correct_top_k(self, retrieval_service, direct):
        """Concurrent clients → micro-batcher → MIPS retrieval → re-rank →
        top-k responses (the end-to-end path test)."""
        rng = np.random.default_rng(3)
        histories = {
            f"c{i}": list(rng.integers(0, NUM_ITEMS, rng.integers(2, 14)))
            for i in range(8)
        }
        responses = {}
        errors = []

        def client(user):
            try:
                responses[user] = retrieval_service.score(
                    user, history=histories[user], timeout=30
                )
            except Exception as exc:  # noqa: BLE001
                errors.append((user, exc))

        threads = [threading.Thread(target=client, args=(u,)) for u in histories]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join()
        assert not errors
        for user, response in responses.items():
            assert response.scores.shape == (5,) and response.item_ids.shape == (5,)
            full = direct(
                histories[user], SEQ_LEN, response.batch_bucket
            )[0].astype(np.float64)
            probs = 1.0 / (1.0 + np.exp(-(full * 1.5 - 0.2)))
            want_ids = np.argsort(-probs, kind="stable")[:5]
            assert set(response.item_ids) == set(want_ids)
            np.testing.assert_allclose(
                np.sort(response.scores), np.sort(probs[want_ids]), rtol=1e-5
            )

    def test_hits_ride_retrieval_too(self, retrieval_service):
        retrieval_service.score("warm", history=[1, 2, 3], timeout=30)
        hit_calls_before = retrieval_service.engine.hit_calls
        hit = retrieval_service.score("warm", timeout=30)
        assert hit.served_from == "hit"
        assert hit.scores.shape == (5,)
        smaller = retrieval_service.score("warm", k=2, timeout=30)
        np.testing.assert_array_equal(smaller.item_ids, hit.item_ids[:2])
        # retrieval-mode hit batches bypass the hidden scorers but must still
        # count toward the fill ratio, or the metric only sees encode lanes
        assert retrieval_service.engine.hit_calls >= hit_calls_before + 2
        assert retrieval_service.stats()["batch_fill_ratio"] > 0.0

    def test_request_validation(self, retrieval_service):
        with pytest.raises(ValueError, match="candidates"):
            retrieval_service.submit("x", history=[1], candidates=[1, 2]).result(10)
        with pytest.raises(ValueError, match="top_k"):
            retrieval_service.submit("x", history=[1], k=50).result(10)


class TestObservability:
    def test_spans_events_and_goodput(self, model_and_params, tmp_path):
        model, params = model_and_params
        tracer = Tracer()
        logger = JsonlLogger(str(tmp_path))
        trace_path = str(tmp_path / "trace.json")
        svc = ScoringService(
            model, params,
            batch_buckets=(1, 4),
            max_wait_ms=10.0,
            tracer=tracer,
            logger=logger,
            trace_path=trace_path,
        )
        with svc:
            svc.score("a", history=[1, 2, 3], timeout=30)
            svc.score("a", new_items=[4], timeout=30)
            svc.score("a", timeout=30)
        logger.close()

        events = [json.loads(line) for line in open(tmp_path / "events.jsonl")]
        kinds = [e["event"] for e in events]
        assert kinds[0] == "on_serve_start"
        assert kinds[-1] == "on_serve_end"
        assert "on_serve_batch" in kinds
        end = events[-1]
        assert end["requests"] == 3 and end["answered"] == 3
        assert end["served_from"] == {"hit": 1, "advance": 1, "cold": 1, "fallback": 0}
        assert end["cache_hit_rate"] == pytest.approx(2.0 / 3.0)
        fractions = end["goodput"]["fractions"]
        assert sum(fractions.values()) == pytest.approx(1.0)
        assert end["goodput"]["input_starvation"] is None  # not a training run

        trace = json.load(open(trace_path))
        names = [e["name"] for e in trace["traceEvents"]]
        # per-request queue_wait spans + per-batch score spans are visible
        assert names.count("queue_wait") == 3
        assert "score" in names and "batch_build" in names
        worker_tids = {e["tid"] for e in trace["traceEvents"] if e["name"] == "score"}
        assert len(worker_tids) == 1  # one serve worker owns the device
