"""ScoringService under fire: deadlines, shedding, breaker, degradation ladder.

The serve-side resilience contract (docs/serving.md "Overload and
degradation"):

* no orphaned waiters — ``close()`` resolves every pending future, a
  ``score(timeout=...)`` expiry cancels the request so batch build skips it,
  and an expired ``deadline_ms`` drops a request BEFORE it reaches the device;
* admission control — bounded lanes fail fast with ``RequestShed``;
* the breaker — consecutive engine failures open it, refused traffic walks
  the ladder (cache_only → fallback → ``CircuitOpen``), recovery re-closes it;
* degraded parity — a cache_only response is bitwise identical to a pure
  cache hit of the same stale state, with ``served_by`` correctly tagged.
"""

import threading
import time
from concurrent.futures import TimeoutError as FutureTimeoutError

import numpy as np
import pytest

import jax

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.nn.sequential.sasrec import SasRec
from replay_tpu.obs import TrainerEvent
from replay_tpu.serve import (
    CircuitBreaker,
    CircuitOpen,
    DeadlineExceeded,
    FallbackScorer,
    RequestShed,
    ScoringService,
)
from replay_tpu.utils.faults import EngineErrorAt, InjectedFault, LatencySpike, wrap_method

pytestmark = [pytest.mark.jax, pytest.mark.smoke]

NUM_ITEMS, SEQ_LEN, DIM = 20, 8, 8


class EventLog:
    """RunLogger stand-in recording every emitted serve event."""

    def __init__(self):
        self.events = []
        self._lock = threading.Lock()

    def log_event(self, event: TrainerEvent) -> None:
        with self._lock:
            self.events.append((event.event, dict(event.payload)))

    def named(self, name):
        with self._lock:
            return [payload for event, payload in self.events if event == name]


@pytest.fixture(scope="module")
def model_and_params():
    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id", FeatureType.CATEGORICAL, is_seq=True,
            feature_hint=FeatureHint.ITEM_ID, cardinality=NUM_ITEMS, embedding_dim=DIM,
        )
    )
    model = SasRec(
        schema=schema, embedding_dim=DIM, num_blocks=1, max_sequence_length=SEQ_LEN
    )
    ids = np.zeros((2, SEQ_LEN), np.int32)
    params = model.init(
        jax.random.PRNGKey(0), {"item_id": ids}, np.ones((2, SEQ_LEN), bool)
    )["params"]
    return model, params


def _service(model_and_params, **kwargs):
    model, params = model_and_params
    kwargs.setdefault("length_buckets", (SEQ_LEN,))
    kwargs.setdefault("batch_buckets", (1, 4))
    kwargs.setdefault("max_wait_ms", 5.0)
    return ScoringService(model, params, **kwargs)


HISTORY = [3, 1, 4, 1, 5]


class TestNoOrphanedWaiters:
    def test_close_resolves_every_pending_future(self, model_and_params):
        """The orphaned-waiter regression: futures pending at close() must be
        resolved — flushed through a healthy worker, or failed — never hung."""
        service = _service(model_and_params).start()
        # a permanently-failing engine: every dispatch errors, so pending
        # futures can only be resolved by failure paths
        wrap_method(service.engine, "encode", EngineErrorAt(at_calls=range(10_000)))
        futures = [
            service.submit(f"u{i}", history=HISTORY) for i in range(8)
        ]
        service.close()
        for future in futures:
            assert future.done(), "a pending future outlived close()"
            assert isinstance(future.exception(), Exception)
        # and the service refuses (fast-fails) new work rather than hanging it
        after = service.submit("late", history=HISTORY)
        assert after.done() and after.exception() is not None

    def test_score_timeout_cancels_and_batch_build_skips(self, model_and_params):
        """A client that gives up must not cost a scoring slot: the cancelled
        request is skipped at batch build (generation-counter style drop)."""
        service = _service(model_and_params, max_wait_ms=1.0).start()
        try:
            spike = LatencySpike(at_calls=[0], duration_s=0.4)
            wrap_method(service.engine, "encode", spike)
            blocker = service.submit("blocker", history=HISTORY)
            deadline = time.perf_counter() + 5.0
            while not spike.injected_at and time.perf_counter() < deadline:
                time.sleep(0.005)  # the worker is now wedged in the spike
            calls_before = service.engine.encode_calls
            with pytest.raises(FutureTimeoutError):
                service.score("impatient", history=HISTORY, timeout=0.05)
            blocker.result(timeout=30)
            time.sleep(0.1)  # let the worker drain the abandoned entry
            stats = service.stats()
            assert stats["cancelled"] >= 1
            # the abandoned request never reached the engine: only the
            # blocker's call landed after the wedge began
            assert service.engine.encode_calls == calls_before + 1
            assert stats["served_from"]["cold"] == 1  # blocker only
        finally:
            service.close()

    def test_deadline_expires_at_batch_build_before_device(self, model_and_params):
        log = EventLog()
        service = _service(model_and_params, max_wait_ms=1.0, logger=log).start()
        try:
            spike = LatencySpike(at_calls=[0], duration_s=0.4)
            wrap_method(service.engine, "encode", spike)
            blocker = service.submit("blocker", history=HISTORY)
            deadline = time.perf_counter() + 5.0
            while not spike.injected_at and time.perf_counter() < deadline:
                time.sleep(0.005)
            doomed = service.submit("doomed", history=HISTORY, deadline_ms=30.0)
            with pytest.raises(DeadlineExceeded) as info:
                doomed.result(timeout=30)
            assert info.value.waited_s >= 0.03 - 1e-3
            blocker.result(timeout=30)
            stats = service.stats()
            assert stats["deadline_misses"] == 1
            assert stats["deadline_miss_rate"] > 0.0
            assert stats["served_from"]["cold"] == 1  # the dropped one never scored
            # a fully-dropped batch still reports its drop accounting: the
            # worst storms must not go dark in the event stream
            dropped = [
                b for b in log.named("on_serve_batch")
                if b["rows"] == 0 and b["dropped_expired"] >= 1
            ]
            assert dropped, log.named("on_serve_batch")
        finally:
            service.close()

    def test_default_deadline_applies_when_request_has_none(self, model_and_params):
        service = _service(
            model_and_params, max_wait_ms=1.0, default_deadline_ms=30.0
        ).start()
        try:
            spike = LatencySpike(at_calls=[0], duration_s=0.4)
            wrap_method(service.engine, "encode", spike)
            blocker = service.submit("blocker", history=HISTORY)
            deadline = time.perf_counter() + 5.0
            while not spike.injected_at and time.perf_counter() < deadline:
                time.sleep(0.005)
            doomed = service.submit("doomed", history=HISTORY)  # no explicit deadline
            with pytest.raises(DeadlineExceeded):
                doomed.result(timeout=30)
            blocker.result(timeout=30)
        finally:
            service.close()


class TestAdmissionControl:
    def test_full_lane_sheds_with_depth_and_event(self, model_and_params):
        log = EventLog()
        service = _service(
            model_and_params, max_queue_depth=1, max_wait_ms=1.0, logger=log
        ).start()
        try:
            spike = LatencySpike(at_calls=[0], duration_s=0.5)
            wrap_method(service.engine, "encode", spike)
            blocker = service.submit("blocker", history=HISTORY)
            deadline = time.perf_counter() + 5.0
            while not spike.injected_at and time.perf_counter() < deadline:
                time.sleep(0.005)
            queued = service.submit("queued", history=HISTORY)  # fills the lane
            shed = service.submit("over", history=HISTORY)
            with pytest.raises(RequestShed) as info:
                shed.result(timeout=5)
            assert info.value.max_depth == 1
            assert info.value.retry_after_s is not None
            # a second shed inside the throttle window: its count coalesces
            # and MUST be flushed at close, not silently dropped
            shed2 = service.submit("over2", history=HISTORY)
            with pytest.raises(RequestShed):
                shed2.result(timeout=5)
            blocker.result(timeout=30)
            queued.result(timeout=30)
            stats = service.stats()
            assert stats["shed"] == 2 and stats["shed_rate"] > 0.0
            shed_events = log.named("on_shed")
            assert shed_events and shed_events[0]["lane"].startswith("encode")
        finally:
            service.close()
        # post-close: the trailing coalesced count was flushed, so summing
        # `count` over events.jsonl reproduces the shed total exactly
        assert sum(e["count"] for e in log.named("on_shed")) == 2

    def test_shed_encode_absorbed_by_cache_only_rung(self, model_and_params):
        """Overload degradation: a warm user's shed encode rides the hit lane
        on its stale cached state instead of failing."""
        log = EventLog()
        service = _service(
            model_and_params, max_queue_depth=1, max_wait_ms=1.0, logger=log
        ).start()
        try:
            service.score("warm", history=HISTORY, timeout=30)  # cache the state
            spike = LatencySpike(at_calls=[0], duration_s=0.5)
            wrap_method(service.engine, "encode", spike)
            blocker = service.submit("blocker", history=HISTORY)
            deadline = time.perf_counter() + 5.0
            while not spike.injected_at and time.perf_counter() < deadline:
                time.sleep(0.005)
            filler = service.submit("filler", history=HISTORY)  # encode lane full
            degraded = service.submit("warm", new_items=[7])
            response = degraded.result(timeout=30)
            assert response.served_by == "cache_only"
            assert any(
                payload["to"] == "cache_only" and payload["reason"] == "overload"
                for payload in log.named("on_degrade")
            )
            blocker.result(timeout=30)
            filler.result(timeout=30)
        finally:
            service.close()


class TestDegradationLadder:
    def test_cache_only_is_bitwise_identical_to_the_pure_hit_path(
        self, model_and_params
    ):
        """THE degraded-parity gate: under an open breaker, a warm user's
        response is bitwise identical to a pure cache hit of the same stale
        state — it IS one — with served_by tagging the rung."""
        service = _service(model_and_params).start()
        try:
            service.score("warm", history=HISTORY, timeout=30)
            reference = service.score("warm", timeout=30)  # pure hit, primary
            assert reference.served_from == "hit"
            assert reference.served_by == "primary"
            for _ in range(service.breaker.failure_threshold):
                service.breaker.record_failure()
            assert service.breaker.state == "open"
            degraded = service.score("warm", new_items=[7], timeout=30)
            assert degraded.served_by == "cache_only"
            assert degraded.served_from == "hit"
            assert degraded.batch_bucket == reference.batch_bucket
            np.testing.assert_array_equal(degraded.scores, reference.scores)
            # the interaction still landed: the window advanced even though
            # the response scored the pre-advance state
            assert service.cache.peek("warm").window[-1] == 7
        finally:
            service.close()

    def test_pure_hits_stay_primary_while_breaker_is_open(self, model_and_params):
        service = _service(model_and_params).start()
        try:
            service.score("warm", history=HISTORY, timeout=30)
            for _ in range(service.breaker.failure_threshold):
                service.breaker.record_failure()
            response = service.score("warm", timeout=30)
            # a pure hit needs no encode — it is NOT degraded traffic
            assert response.served_by == "primary"
            assert response.served_from == "hit"
        finally:
            service.close()

    def test_fallback_floor_serves_cold_traffic_when_open(self, model_and_params):
        log = EventLog()
        fallback = FallbackScorer(np.arange(NUM_ITEMS, dtype=np.float32))
        service = _service(model_and_params, fallback=fallback, logger=log).start()
        try:
            for _ in range(service.breaker.failure_threshold):
                service.breaker.record_failure()
            response = service.score("brand-new", history=HISTORY, timeout=30)
            assert response.served_by == "fallback"
            assert response.served_from == "fallback"
            want_scores, want_ids = fallback.score()
            np.testing.assert_array_equal(response.scores, want_scores)
            assert response.item_ids is None and want_ids is None
            topk = service.score("another-new", history=HISTORY, k=3, timeout=30)
            np.testing.assert_array_equal(
                topk.item_ids, [NUM_ITEMS - 1, NUM_ITEMS - 2, NUM_ITEMS - 3]
            )
            assert fallback.served == 2
            assert service.stats()["served_by"]["fallback"] == 2
            assert any(
                payload["to"] == "fallback" for payload in log.named("on_degrade")
            )
        finally:
            service.close()

    def test_circuit_open_without_any_degraded_mode(self, model_and_params):
        service = _service(
            model_and_params,
            breaker=CircuitBreaker(failure_threshold=1, reset_timeout_s=60.0),
        ).start()
        try:
            service.breaker.record_failure()
            future = service.submit("cold-new", history=HISTORY)
            with pytest.raises(CircuitOpen) as info:
                future.result(timeout=5)
            assert info.value.retry_after_s == pytest.approx(60.0, abs=1.0)
            assert service.stats()["circuit_refusals"] == 1
        finally:
            service.close()


class TestBreakerIntegration:
    def test_consecutive_engine_failures_open_then_probe_recloses(
        self, model_and_params
    ):
        """The full round trip against a REAL engine: injected failures trip
        the breaker, the reset window passes, the half-open probe succeeds
        (injector exhausted) and traffic is primary again."""
        log = EventLog()
        service = _service(
            model_and_params,
            breaker=CircuitBreaker(failure_threshold=2, reset_timeout_s=0.15),
            logger=log,
        ).start()
        try:
            injector = EngineErrorAt(at_calls=range(2))
            wrap_method(service.engine, "encode", injector)
            for i in range(2):
                future = service.submit(f"trip{i}", history=HISTORY)
                with pytest.raises(InjectedFault):
                    future.result(timeout=30)
            assert service.breaker.state == "open"
            # the injector raises BEFORE the real encode (no device work), so
            # the failures are counted where the breaker lives: at dispatch
            assert service.breaker.stats()["failures"] == 2
            time.sleep(0.2)  # past the reset window: next encode is the probe
            response = service.score("probe", history=HISTORY, timeout=30)
            assert response.served_by == "primary"
            assert service.breaker.state == "closed"
            stats = service.breaker.stats()
            assert stats["opens"] == 1 and stats["closes"] == 1
            transitions = [(p["from"], p["to"]) for p in log.named("on_breaker")]
            assert transitions == [
                ("closed", "open"), ("open", "half_open"), ("half_open", "closed"),
            ]
        finally:
            service.close()

    def test_caller_supplied_transition_hook_is_chained_not_clobbered(
        self, model_and_params
    ):
        """A user's CircuitBreaker(on_transition=alerting_hook) keeps firing
        after the service wires its own event forwarding — and a raising hook
        never poisons the dispatch path."""
        seen = []

        def hook(old, new, info):
            seen.append((old, new))
            raise RuntimeError("pager down")  # must be contained

        log = EventLog()
        service = _service(
            model_and_params,
            breaker=CircuitBreaker(failure_threshold=1, on_transition=hook),
            logger=log,
        ).start()
        try:
            wrap_method(service.engine, "encode", EngineErrorAt(at_calls=[0]))
            future = service.submit("trip", history=HISTORY)
            with pytest.raises(InjectedFault):
                future.result(timeout=30)
            assert seen == [("closed", "open")]
            assert [(p["from"], p["to"]) for p in log.named("on_breaker")] == [
                ("closed", "open")
            ]
        finally:
            service.close()

    def test_stats_and_serve_end_carry_resilience_totals(self, model_and_params):
        log = EventLog()
        service = _service(model_and_params, logger=log).start()
        service.score("u", history=HISTORY, timeout=30)
        service.close()
        stats = service.stats()
        for key in (
            "shed", "deadline_misses", "cancelled", "circuit_refusals",
            "degraded", "shed_rate", "deadline_miss_rate", "error_rate",
            "served_by", "breaker",
        ):
            assert key in stats, key
        assert stats["served_by"]["primary"] == 1
        assert stats["degraded"] == 0
        (end,) = log.named("on_serve_end")
        assert end["shed_rate"] == 0.0 and end["breaker"]["state"] == "closed"
