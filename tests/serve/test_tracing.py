"""End-to-end distributed request tracing: context propagation, the merged
multi-track trace, latency exemplars, and tail attribution.

Host-only (fake replicas, the ``test_router.py`` strategy): the tentpole's
claims live here — a ``TraceContext`` minted at fleet admission rides every
hop as pure JSON, the router's spans and each replica's spans merge into ONE
Chrome trace where a hedged request's spans share a trace_id across tracks,
the latency histogram keeps bounded slowest-N exemplar trace ids, and
``obs.report`` decomposes the tail into per-hop fractions summing to 1.0
(with ``--compare`` gating shifts in the p99 hop mix). The off switch is a
contract too: tracing disabled must inject no kwarg and allocate no context.
"""

import json
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from replay_tpu.obs import (
    MetricsLogger,
    REQUEST_HOP_SPANS,
    TraceContext,
    Tracer,
    TrainerEvent,
    lifecycle_span,
    merge_traces,
    tail_attribution,
)
from replay_tpu.obs.report import compare_runs, load_trace, load_trace_events, render
from replay_tpu.serve import BackoffPolicy, RequestShed, ServingFleet
from replay_tpu.serve.request import ScoreResponse

pytestmark = pytest.mark.core


class TracedFakeService:
    """A replica stand-in honoring the tracing contract: accepts the
    ``_trace`` kwarg and records its ``queue_wait`` span (cross-thread, via
    :func:`lifecycle_span`) keyed by the forwarded trace_id — the way the
    real ``ScoringService`` dispatch path does."""

    def __init__(self, name, delay_s=0.0, shed_first=0, tracer=None):
        self.name = name
        self.delay_s = delay_s
        self.shed_remaining = shed_first
        self.tracer = tracer if tracer is not None else Tracer(enabled=False)
        self.alive = True
        self.submits = 0
        self.submitted_kwargs = []
        self.closed = False

    def start(self):
        return self

    def close(self):
        self.closed = True
        self.alive = False

    def heartbeat(self):
        if not self.alive:
            raise RuntimeError(f"{self.name} is down")
        return {
            "live": True,
            "queued": 0,
            "max_depth": 16,
            "breaker_state": "closed",
            "requests": self.submits,
            "errors": 0,
        }

    def stats(self):
        return {"submits": self.submits}

    def submit(self, user_id, _trace=None, **kwargs):
        self.submits += 1
        self.submitted_kwargs.append({"_trace": _trace, **kwargs})
        future = Future()
        if self.shed_remaining > 0:
            self.shed_remaining -= 1
            future.set_exception(
                RequestShed(("encode", 1), 16, 16, retry_after_s=0.005)
            )
            return future
        enqueued_at = self.tracer.now()

        def resolve():
            if _trace is not None:
                lifecycle_span(
                    self.tracer, "queue_wait", enqueued_at,
                    trace_id=_trace.get("trace_id"), lane="hit",
                )
            if future.set_running_or_notify_cancel():
                future.set_result(
                    ScoreResponse(
                        user_id=user_id,
                        scores=np.zeros(3),
                        item_ids=None,
                        served_from="hit",
                        lane="hit",
                        queue_wait_s=0.0,
                    )
                )

        if self.delay_s:
            timer = threading.Timer(self.delay_s, resolve)
            timer.daemon = True
            timer.start()
        else:
            resolve()
        return future


def _traced_fleet(replicas, **kwargs):
    """A fleet with the full tracing plane on: router tracer + one live
    tracer per replica, plus the label->tracer map for merge_traces."""
    router_tracer = Tracer(enabled=True)
    tracers = {name: Tracer(enabled=True) for name in replicas}
    services = {
        name: TracedFakeService(name, tracer=tracers[name], **replicas[name])
        for name in replicas
    }
    kwargs.setdefault("heartbeat_interval_s", None)
    kwargs.setdefault("hedge_ms", 0)
    fleet = ServingFleet(services, tracer=router_tracer, **kwargs)
    return fleet, services, {"router": router_tracer, **tracers}


class TestTraceContext:
    def test_mint_child_and_json_round_trip(self):
        context = TraceContext.mint()
        assert context.trace_id.startswith("t-")
        assert context.parent_span is None
        child = context.child("route")
        assert child.trace_id == context.trace_id
        assert child.parent_span == "route"
        payload = child.to_json()
        # the socket-boundary contract: plain JSON strings, nothing richer
        assert json.loads(json.dumps(payload)) == payload
        restored = TraceContext.from_json(payload)
        assert restored.trace_id == context.trace_id
        assert restored.parent_span == "route"
        assert TraceContext.from_json(None) is None
        assert TraceContext.from_json({}) is None

    def test_minted_ids_are_unique(self):
        ids = {TraceContext.mint().trace_id for _ in range(500)}
        assert len(ids) == 500


class TestFleetPropagation:
    def test_trace_rides_every_hop_and_stamps_the_response(self):
        fleet, services, _ = _traced_fleet({"a": {}, "b": {}})
        with fleet:
            response = fleet.score(7, timeout=5)
        assert response.trace_id is not None
        home = services[response.replica]
        forwarded = home.submitted_kwargs[-1]["_trace"]
        assert forwarded["trace_id"] == response.trace_id
        assert forwarded["parent_span"] == "route"

    def test_router_records_route_and_request_root_spans(self):
        fleet, _, tracers = _traced_fleet({"a": {}, "b": {}})
        with fleet:
            response = fleet.score(7, timeout=5)
        summary = tracers["router"].summary()
        assert summary["route"]["count"] == 1
        assert summary["request"]["count"] == 1
        events = tracers["router"].to_chrome_trace()["traceEvents"]
        root = next(e for e in events if e["name"] == "request")
        assert root["args"]["trace_id"] == response.trace_id
        assert root["args"]["served_by"] == "primary"
        # the root spans admission -> answer: it must cover the route hop
        route = next(e for e in events if e["name"] == "route")
        assert route["args"]["trace_id"] == response.trace_id
        assert root["dur"] >= route["dur"]

    def test_tracing_off_injects_nothing(self):
        """The zero-allocation contract: no tracer => no context minted, no
        ``_trace`` kwarg injected (duck-typed replicas without the parameter
        keep working), no trace_id on the response."""
        services = {"a": TracedFakeService("a"), "b": TracedFakeService("b")}
        fleet = ServingFleet(services, heartbeat_interval_s=None, hedge_ms=0)
        with fleet:
            response = fleet.score(7, timeout=5)
        assert response.trace_id is None
        assert not fleet.tracer.enabled
        for service in services.values():
            for kwargs in service.submitted_kwargs:
                assert kwargs["_trace"] is None
        assert fleet.stats()["latency_exemplars"] == []

    def test_retry_records_backoff_wait_on_the_timeline(self):
        fleet, _, tracers = _traced_fleet(
            {"s": {"shed_first": 1}},
            backoff=BackoffPolicy(base_s=0.001, max_retries=2),
        )
        with fleet:
            response = fleet.score(1, timeout=5)
        events = tracers["router"].to_chrome_trace()["traceEvents"]
        backoff = next(e for e in events if e["name"] == "backoff_wait")
        assert backoff["args"]["trace_id"] == response.trace_id
        assert backoff["args"]["error"] == "RequestShed"
        assert backoff["dur"] > 0


class TestHedgedTimeline:
    def test_hedged_request_spans_share_one_trace_id_across_tracks(self):
        """The tentpole's acceptance render: one hedged request = router
        ``hedge_wait`` + BOTH replicas' ``queue_wait`` spans, all carrying
        the same trace_id, landing on different pids in the merged trace."""
        fleet, services, tracers = _traced_fleet(
            {"slow": {"delay_s": 0.5}, "b": {}, "c": {}}, hedge_ms=25
        )
        with fleet:
            user = next(u for u in range(200) if fleet.ring.route(u) == "slow")
            response = fleet.score(user, timeout=5)
        assert response.replica != "slow"
        merged = merge_traces(tracers)
        by_pid = {}
        for event in merged["traceEvents"]:
            if response.trace_id in (
                [event.get("args", {}).get("trace_id")]
                + list(event.get("args", {}).get("trace_ids") or [])
            ):
                by_pid.setdefault(event["pid"], []).append(event["name"])
        # router track + the winning replica (the slow loser was cancelled
        # before resolving, so its queue_wait span may never record)
        assert len(by_pid) >= 2, by_pid
        router_pid = merged["otherData"]["tracks"]["router"]
        assert "hedge_wait" in by_pid[router_pid]
        assert "request" in by_pid[router_pid]
        winner_pid = merged["otherData"]["tracks"][response.replica]
        assert "queue_wait" in by_pid[winner_pid]
        stats = fleet.stats()
        assert stats["per_replica"][response.replica]["hedge_wins"] == 1
        assert stats["per_replica"]["slow"]["hedge_cancelled"] == 1

    def test_cross_thread_lifecycle_spans_survive_a_mid_span_cancel(self):
        """Satellite hardening: the loser replica is cancelled while its
        (timer-thread) lifecycle span is still open. Every recorded span must
        still come out well-formed — non-negative durations, correct
        per-thread attribution, loadable as a Chrome trace."""
        fleet, services, tracers = _traced_fleet(
            {"slow": {"delay_s": 0.2}, "b": {}}, hedge_ms=10
        )
        with fleet:
            user = next(u for u in range(200) if fleet.ring.route(u) == "slow")
            for _ in range(3):
                fleet.score(user, timeout=5)
        # let the slow loser's timers fire their (post-cancel) resolve
        time.sleep(0.5)
        merged = merge_traces(tracers)
        tids = set()
        for event in merged["traceEvents"]:
            if event.get("ph") == "M":
                continue
            assert event["dur"] >= 0
            assert event["ts"] >= 0
            tids.add((event["pid"], event["tid"]))
        # spans were recorded from more than one thread (client + timer)
        assert len(tids) >= 2
        # and the loser's queue_wait, when it DID record, kept its trace args
        slow_pid = merged["otherData"]["tracks"]["slow"]
        for event in merged["traceEvents"]:
            if event["pid"] == slow_pid and event.get("ph") != "M":
                assert event["name"] == "queue_wait"
                assert event["args"]["trace_id"].startswith("t-")


class TestExemplars:
    def test_fleet_keeps_bounded_slowest_n_exemplars(self):
        fleet, _, _ = _traced_fleet({"a": {}, "b": {}})
        with fleet:
            responses = [fleet.score(user, timeout=5) for user in range(20)]
            stats = fleet.stats()
        exemplars = stats["latency_exemplars"]
        assert 0 < len(exemplars) <= 8
        answered_ids = {r.trace_id for r in responses}
        for record in exemplars:
            assert record["trace_id"] in answered_ids
            assert record["latency_ms"] >= 0
        # slowest-first ordering
        latencies = [record["latency_ms"] for record in exemplars]
        assert latencies == sorted(latencies, reverse=True)

    def test_histogram_exemplar_store_keeps_the_slowest(self):
        from replay_tpu.obs.metrics import Histogram

        histogram = Histogram(buckets=(1.0, 10.0, 100.0))
        for i in range(50):
            histogram.observe(float(i), exemplar=f"t-{i}")
        kept = histogram.exemplars()
        assert len(kept) == Histogram.EXEMPLAR_CAPACITY
        assert [record["value"] for record in kept] == [
            49.0, 48.0, 47.0, 46.0, 45.0, 44.0, 43.0, 42.0
        ]
        assert kept[0]["trace_id"] == "t-49"
        assert histogram.sample()["exemplars"] == kept
        # exemplar-free histograms pay (and expose) nothing
        assert "exemplars" not in Histogram(buckets=(1.0,)).sample()

    def test_metrics_bridge_surfaces_fleet_exemplars_on_snapshot(self):
        bridge = MetricsLogger()
        bridge.log_event(
            TrainerEvent(
                "on_fleet_end",
                payload={
                    "requests": 10,
                    "latency_exemplars": [
                        {"latency_ms": 120.5, "trace_id": "t-slow"},
                        {"latency_ms": 80.0, "trace_id": "t-slower"},
                    ],
                },
            )
        )
        snapshot = bridge.registry.snapshot()
        series = snapshot["replay_fleet_latency_exemplar_ms"]
        assert series["count"] == 2
        kept = {record["trace_id"] for record in series["exemplars"]}
        assert kept == {"t-slow", "t-slower"}


class TestMergedTraceAndAttribution:
    def test_merge_aligns_epochs_and_labels_tracks(self, tmp_path):
        early, late = Tracer(enabled=True), Tracer(enabled=True)
        early._wall0, late._wall0 = 100.0, 100.25  # late started 250 ms after
        early.add_span("request", 0.0, 0.010, trace_id="t-x")
        late.add_span("queue_wait", 0.0, 0.004, trace_id="t-x")
        path = str(tmp_path / "trace.json")
        merged = merge_traces({"router": early, "r0": late}, path)
        assert merged["otherData"]["tracks"] == {"router": 1, "r0": 2}
        names = {
            (e["pid"], e["name"]): e
            for e in merged["traceEvents"]
            if e.get("ph") != "M"
        }
        # the late shard's events shifted onto the early epoch: +250 ms
        assert names[(2, "queue_wait")]["ts"] == pytest.approx(250_000.0)
        assert names[(1, "request")]["ts"] == pytest.approx(0.0)
        meta = [e for e in merged["traceEvents"] if e.get("ph") == "M"]
        assert {e["args"]["name"] for e in meta} == {"router", "r0"}
        # the written file round-trips through the report loader, and the
        # M events stay out of the name-level aggregation
        aggregated = load_trace(path)
        assert set(aggregated) == {"request", "queue_wait"}
        assert len(load_trace_events(path)) == 4

    def test_tail_attribution_fractions_sum_to_one(self):
        tracer = Tracer(enabled=True)
        # 99 fast requests: 10 ms total, 4 ms queue_wait + 4 ms score
        for i in range(99):
            tid = f"t-fast-{i}"
            tracer.add_span("request", 0.0, 0.010, trace_id=tid)
            tracer.add_span("queue_wait", 0.0, 0.004, trace_id=tid)
            tracer.add_span("score", 0.004, 0.004, trace_ids=[tid])
        # one disaster: 1 s total, 900 ms queue_wait
        tracer.add_span("request", 0.0, 1.0, trace_id="t-slow")
        tracer.add_span("queue_wait", 0.0, 0.9, trace_id="t-slow")
        events = tracer.to_chrome_trace()["traceEvents"]
        attribution = tail_attribution(events)
        assert attribution["requests"] == 100
        assert attribution["hops"] == list(REQUEST_HOP_SPANS) + ["other"]
        for entry in attribution["quantiles"].values():
            fractions = entry["fractions"]
            assert sum(fractions.values()) == pytest.approx(1.0)
            assert all(f >= 0.0 for f in fractions.values())
        p99 = attribution["quantiles"]["p99"]
        assert p99["n"] == 1
        assert p99["latency_ms"] == pytest.approx(1000.0)
        assert p99["fractions"]["queue_wait"] == pytest.approx(0.9)
        p50 = attribution["quantiles"]["p50"]
        # the median mix is dominated by the fast requests' 40/40/20 split
        assert p50["fractions"]["queue_wait"] < 0.5

    def test_tail_attribution_none_without_traced_roots(self):
        tracer = Tracer(enabled=True)
        tracer.add_span("train_step", 0.0, 0.01)  # a training trace
        assert tail_attribution(tracer.to_chrome_trace()["traceEvents"]) is None
        assert tail_attribution([]) is None

    def test_overlapping_hops_renormalize_within_the_root(self):
        """A hedged request's hop seconds can exceed its root window (two
        replicas racing): the per-request fractions must still sum to 1.0."""
        tracer = Tracer(enabled=True)
        tracer.add_span("request", 0.0, 0.010, trace_id="t-h")
        tracer.add_span("queue_wait", 0.0, 0.009, trace_id="t-h")  # primary
        tracer.add_span("queue_wait", 0.002, 0.008, trace_id="t-h")  # twin
        attribution = tail_attribution(tracer.to_chrome_trace()["traceEvents"])
        fractions = attribution["quantiles"]["p99"]["fractions"]
        assert sum(fractions.values()) == pytest.approx(1.0)
        assert fractions["queue_wait"] == pytest.approx(1.0)


class TestCompareGate:
    @staticmethod
    def _summary(queue_share):
        score_share = max(0.9 - queue_share, 0.0)
        return {
            "source": "x",
            "tail_attribution": {
                "requests": 100,
                "hops": ["queue_wait", "score", "other"],
                "quantiles": {
                    "p99": {
                        "latency_ms": 50.0,
                        "n": 1,
                        "fractions": {
                            "queue_wait": queue_share,
                            "score": score_share,
                            "other": 0.1,
                        },
                    }
                },
            },
        }

    def test_p99_hop_share_shift_gates_even_with_flat_p99(self):
        lines, regressions = compare_runs(
            self._summary(0.55), self._summary(0.30)
        )
        assert any("tail_p99_share/queue_wait" in r for r in regressions), (
            lines, regressions,
        )

    def test_small_shift_is_surfaced_not_gated(self):
        lines, regressions = compare_runs(
            self._summary(0.35), self._summary(0.30)
        )
        assert not any("tail_p99_share" in r for r in regressions)
        assert any("tail_p99_share/queue_wait" in line for line in lines)

    def test_chaos_mismatch_suppresses_the_gate(self):
        candidate = self._summary(0.55)
        candidate["fleet"] = {"chaos": {"killed": "r1"}}
        baseline = self._summary(0.30)
        baseline["fleet"] = {}
        _, regressions = compare_runs(candidate, baseline)
        assert not any("tail_p99_share" in r for r in regressions)

    def test_render_shows_tail_attribution(self):
        text = render(self._summary(0.55))
        assert "tail attribution" in text
        assert "p99" in text and "queue_wait 55%" in text
