import numpy as np
import pandas as pd
import pytest

from replay_tpu.splitters import (
    ColdUserRandomSplitter,
    KFolds,
    LastNSplitter,
    NewUsersSplitter,
    RandomNextNSplitter,
    RandomSplitter,
    RatioSplitter,
    TimeSplitter,
    TwoStageSplitter,
)


@pytest.fixture
def interactions():
    return pd.DataFrame(
        {
            "query_id": [1, 1, 1, 1, 2, 2, 2, 2, 3, 3, 3, 3],
            "item_id": [1, 2, 3, 4, 1, 2, 3, 4, 1, 2, 3, 4],
            "timestamp": [0, 1, 2, 3, 0, 1, 2, 3, 0, 1, 2, 3],
        }
    )


def test_ratio_splitter(interactions):
    train, test = RatioSplitter(test_size=0.5).split(interactions)
    assert len(train) == 6 and len(test) == 6
    for q in (1, 2, 3):
        assert sorted(test[test.query_id == q]["timestamp"]) == [2, 3]


def test_ratio_splitter_quantity(interactions):
    train, test = RatioSplitter(test_size=0.25, split_by_fractions=False).split(interactions)
    assert len(test) == 3
    assert (test.groupby("query_id").size() == 1).all()


def test_ratio_min_interactions(interactions):
    small = interactions[interactions.query_id != 1]
    train, test = RatioSplitter(test_size=0.5, min_interactions_per_group=5).split(small)
    assert len(test) == 0


def test_time_splitter(interactions):
    train, test = TimeSplitter(time_threshold=2).split(interactions)
    assert set(train["timestamp"]) == {0, 1}
    assert set(test["timestamp"]) == {2, 3}


def test_time_splitter_ratio(interactions):
    train, test = TimeSplitter(time_threshold=0.25).split(interactions)
    assert set(test["timestamp"]) == {3}


def test_last_n_splitter(interactions):
    train, test = LastNSplitter(N=2, divide_column="query_id").split(interactions)
    assert len(test) == 6
    assert set(test["timestamp"]) == {2, 3}


def test_last_n_timedelta(interactions):
    train, test = LastNSplitter(N=2, strategy="timedelta").split(interactions)
    assert set(test["timestamp"]) == {2, 3}


def test_random_splitter(interactions):
    train, test = RandomSplitter(test_size=0.25, seed=0).split(interactions)
    assert len(train) + len(test) == len(interactions)
    assert len(test) == 3


def test_cold_user_splitter(interactions):
    train, test = ColdUserRandomSplitter(test_size=0.34, seed=0).split(interactions)
    test_users = set(test.query_id)
    assert test_users.isdisjoint(set(train.query_id))
    assert len(test_users) == 1


def test_new_users_splitter():
    df = pd.DataFrame(
        {
            "query_id": [1, 1, 2, 2, 3, 3],
            "item_id": [1, 2, 1, 2, 1, 2],
            "timestamp": [0, 5, 1, 6, 4, 7],
        }
    )
    # ceil(0.34 * 3) = 2 newest users go to test (reference cumulative semantics)
    train, test = NewUsersSplitter(test_size=0.34).split(df)
    assert set(test.query_id) == {2, 3}
    # train only keeps rows strictly before the first new user's arrival
    assert train["timestamp"].max() < 1
    train, test = NewUsersSplitter(test_size=0.1).split(df)
    assert set(test.query_id) == {3}


def test_random_next_n_splitter(interactions):
    train, test = RandomNextNSplitter(N=1, seed=0).split(interactions)
    assert (test.groupby("query_id").size() <= 1).all()
    assert len(train) + len(test) <= len(interactions)


def test_two_stage_splitter(interactions):
    train, test = TwoStageSplitter(first_divide_size=1, second_divide_size=0.5, seed=3).split(interactions)
    assert len(set(test.query_id)) == 1
    assert len(test) == 2


def test_kfolds(interactions):
    folds = list(KFolds(n_folds=2, seed=0).split(interactions))
    assert len(folds) == 2
    for train, test in folds:
        assert len(train) + len(test) == len(interactions)


def test_drop_cold_items(interactions):
    df = interactions.copy()
    # make item 4 occur only in the test tail
    train, test = LastNSplitter(N=1, drop_cold_items=True).split(df)
    assert set(test.item_id).issubset(set(train.item_id))


def test_session_recovery():
    df = pd.DataFrame(
        {
            "query_id": [1, 1, 1, 1],
            "item_id": [1, 2, 3, 4],
            "timestamp": [0, 1, 2, 3],
            "session_id": [7, 7, 7, 8],
        }
    )
    train, test = LastNSplitter(N=2, session_id_column="session_id").split(df)
    # session 7 straddles the boundary -> moved wholly to test by default
    assert len(test) == 4
    train, test = LastNSplitter(
        N=2, session_id_column="session_id", session_id_processing_strategy="train"
    ).split(df)
    assert sorted(test["item_id"]) == [4]


def test_save_load(tmp_path, interactions):
    splitter = RatioSplitter(test_size=0.5)
    splitter.save(str(tmp_path / "sp"))
    loaded = RatioSplitter.load(str(tmp_path / "sp"))
    assert loaded.test_size == 0.5
    t1, v1 = splitter.split(interactions)
    t2, v2 = loaded.split(interactions)
    pd.testing.assert_frame_equal(t1, t2)
