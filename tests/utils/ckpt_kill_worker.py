"""Worker for the SIGKILL-mid-save checkpoint atomicity test.

Phase "baseline" writes checkpoint step 1 and exits cleanly. The kill phases
then attempt step 2 but die by real SIGKILL at a chosen point inside
``save_pytree`` — the patched ``_atomic_replace`` pins WHERE in the write
sequence the kill lands (the kill itself is the genuine uncatchable signal,
the patch only makes its timing deterministic):

* ``mid_payload``  — dies while the ``.npz`` payload bytes are still going to
  the ``.tmp`` sibling: the visible directory must show a stray tmp, never a
  torn ``step_2.npz``;
* ``pre_sidecar``  — dies after the payload was atomically published but
  before the JSON commit marker: ``step_2.npz`` exists, ``step_2.json`` does
  not, and the manager must treat the step as never-saved.

The parent test (tests/utils/test_checkpoint.py) asserts ``valid_steps``
skips the partial step and that step 1 restores bit-identically afterwards.
"""

import os
import signal
import sys

import numpy as np

import replay_tpu.utils.checkpoint as ck
from replay_tpu.utils.checkpoint import CheckpointManager


def make_tree(step: int) -> dict:
    rng = np.random.default_rng(7)
    return {
        "w": rng.normal(size=(64, 16)).astype(np.float32),
        "b": rng.normal(size=(16,)).astype(np.float32),
        "step": np.int64(step),
    }


def main() -> None:
    ckpt_dir, phase = sys.argv[1], sys.argv[2]
    manager = CheckpointManager(ckpt_dir, max_to_keep=10)
    if phase == "baseline":
        manager.save(1, make_tree(1))
        assert manager.latest_step() == 1
        return

    original = ck._atomic_replace

    def killing_replace(path, write):
        if phase == "mid_payload" and path.suffix == ".npz":
            # some payload bytes reached the tmp sibling, then the OS kill —
            # exactly the on-disk state a preemption mid-write leaves behind
            with open(path.with_name(path.name + ".tmp"), "wb") as fh:
                fh.write(b"\x00" * 128)
                fh.flush()
                os.fsync(fh.fileno())
            os.kill(os.getpid(), signal.SIGKILL)
        if phase == "pre_sidecar" and path.name.startswith("step_") and path.suffix == ".json":
            # payload published, commit marker not yet written
            os.kill(os.getpid(), signal.SIGKILL)
        original(path, write)

    ck._atomic_replace = killing_replace
    manager.save(2, make_tree(2))
    raise AssertionError(f"phase {phase} survived save(2)")  # pragma: no cover


if __name__ == "__main__":
    main()
