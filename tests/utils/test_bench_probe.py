"""bench.py backend-health probe: bounded retry-with-backoff semantics.

One transient tunnel hiccup (a failed or timed-out probe subprocess) must not
force the CPU-fallback path; a persistently dead backend must still fail fast
after the bounded attempts.
"""

import importlib.util
import os
import subprocess
import types

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench", os.path.join(REPO, "bench.py"))
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


@pytest.mark.core
def test_probe_retries_once_after_transient_failure(bench, monkeypatch):
    calls = []
    sleeps = []

    def flaky_run(*args, **kwargs):
        calls.append(args)
        returncode = 1 if len(calls) == 1 else 0
        return types.SimpleNamespace(returncode=returncode)

    monkeypatch.setattr(bench.subprocess, "run", flaky_run)
    monkeypatch.setattr(bench.time, "sleep", sleeps.append)
    assert bench._backend_healthy(timeout=1.0, attempts=2, backoff=0.01) is True
    assert len(calls) == 2  # first failed, retry succeeded
    assert sleeps == [0.01]  # backed off exactly once


@pytest.mark.core
def test_probe_timeout_counts_as_failed_attempt(bench, monkeypatch):
    calls = []

    def timing_out_run(cmd, **kwargs):
        calls.append(cmd)
        if len(calls) == 1:
            raise subprocess.TimeoutExpired(cmd=cmd, timeout=kwargs.get("timeout") or 0)
        return types.SimpleNamespace(returncode=0)

    monkeypatch.setattr(bench.subprocess, "run", timing_out_run)
    monkeypatch.setattr(bench.time, "sleep", lambda _: None)
    assert bench._backend_healthy(timeout=1.0, attempts=2, backoff=0.0) is True
    assert len(calls) == 2


@pytest.mark.core
def test_probe_gives_up_after_bounded_attempts(bench, monkeypatch):
    calls = []
    monkeypatch.setattr(
        bench.subprocess,
        "run",
        lambda *a, **k: (calls.append(a), types.SimpleNamespace(returncode=1))[1],
    )
    monkeypatch.setattr(bench.time, "sleep", lambda _: None)
    assert bench._backend_healthy(timeout=1.0, attempts=2, backoff=0.0) is False
    assert len(calls) == 2  # bounded: no endless retry loop
