"""Checkpointing: pytree round-trip, retention, kill-and-resume loss-curve parity."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
from replay_tpu.nn.loss import CE
from replay_tpu.nn.sequential.sasrec import SasRec
from replay_tpu.utils.checkpoint import CheckpointManager, load_metadata, restore_pytree, save_pytree

NUM_ITEMS = 10
SEQ_LEN = 5
BATCH = 8


def make_batch(seed: int) -> dict:
    rng = np.random.default_rng(seed)
    items = rng.integers(0, NUM_ITEMS, size=(BATCH, SEQ_LEN + 1)).astype(np.int32)
    mask = np.ones((BATCH, SEQ_LEN), dtype=bool)
    return {
        "feature_tensors": {"item_id": items[:, :-1]},
        "padding_mask": mask,
        "positive_labels": items[:, 1:, None],
        "target_padding_mask": mask[:, :, None],
    }


def make_trainer(learning_rate: float = 1e-2) -> Trainer:
    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id",
            FeatureType.CATEGORICAL,
            is_seq=True,
            feature_hint=FeatureHint.ITEM_ID,
            cardinality=NUM_ITEMS,
            embedding_dim=8,
        )
    )
    model = SasRec(schema=schema, embedding_dim=8, num_blocks=1, max_sequence_length=SEQ_LEN)
    return Trainer(model=model, loss=CE(), optimizer=OptimizerFactory(learning_rate=learning_rate),
                   mesh=make_mesh(), seed=0)


@pytest.mark.jax
def test_pytree_roundtrip_and_validation(tmp_path):
    tree = {"a": jnp.arange(6).reshape(2, 3), "b": [jnp.zeros(4), jnp.ones(())]}
    save_pytree(str(tmp_path / "ckpt"), tree, {"note": "x"})
    restored = restore_pytree(str(tmp_path / "ckpt"), jax.tree.map(np.zeros_like, tree))
    jax.tree.map(np.testing.assert_array_equal, jax.tree.map(np.asarray, tree), restored)
    assert load_metadata(str(tmp_path / "ckpt"))["note"] == "x"
    with pytest.raises(ValueError, match="leaves"):
        restore_pytree(str(tmp_path / "ckpt"), {"a": np.zeros((2, 3))})
    with pytest.raises(ValueError, match="shape"):
        restore_pytree(
            str(tmp_path / "ckpt"), {"a": np.zeros((9, 9)), "b": [np.zeros(4), np.ones(())]}
        )


@pytest.mark.jax
def test_kill_and_resume_reproduces_loss_curve(tmp_path):
    """3 steps + save + restore + 3 steps == 6 uninterrupted steps, exactly."""
    batches = [make_batch(i) for i in range(6)]

    trainer_a = make_trainer()
    state = trainer_a.init_state(batches[0])
    losses_a = []
    for batch in batches:
        state, loss_value = trainer_a.train_step(state, batch)
        losses_a.append(float(loss_value))

    trainer_b = make_trainer()
    state_b = trainer_b.init_state(batches[0])
    losses_b = []
    for batch in batches[:3]:
        state_b, loss_value = trainer_b.train_step(state_b, batch)
        losses_b.append(float(loss_value))
    trainer_b.save_checkpoint(str(tmp_path / "mid"), state_b)

    trainer_c = make_trainer()  # fresh process equivalent
    state_c = trainer_c.restore_checkpoint(str(tmp_path / "mid"), batches[0])
    assert int(state_c.step) == 3
    for batch in batches[3:]:
        state_c, loss_value = trainer_c.train_step(state_c, batch)
        losses_b.append(float(loss_value))

    np.testing.assert_allclose(np.array(losses_a), np.array(losses_b), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6),
        state.params,
        state_c.params,
    )


@pytest.mark.jax
def test_manager_retention_and_history(tmp_path):
    manager = CheckpointManager(str(tmp_path / "run"), max_to_keep=2)
    assert manager.latest_step() is None
    tree = {"w": jnp.ones(3)}
    for step in (1, 2, 3):
        manager.save(step, tree, history=[{"epoch": step, "train_loss": 1.0 / step}])
    assert manager.all_steps() == [2, 3]
    assert manager.latest_step() == 3
    restored = manager.restore({"w": np.zeros(3)})
    np.testing.assert_array_equal(restored["w"], np.ones(3))
    assert manager.history()[-1]["epoch"] == 3
    with pytest.raises(FileNotFoundError):
        CheckpointManager(str(tmp_path / "empty")).restore({"w": np.zeros(3)})


@pytest.mark.jax
def test_sigkill_mid_save_never_corrupts_the_manager(tmp_path):
    """Hard-kill atomicity: a writer SIGKILLed inside ``save_pytree`` — while
    payload bytes are in flight, or after the payload but before the JSON
    commit marker — leaves the directory in a state where ``valid_steps``
    skips the partial step and the PRIOR step restores bit-identically."""
    import subprocess
    import sys
    from pathlib import Path

    worker = Path(__file__).with_name("ckpt_kill_worker.py")
    ckpt_dir = tmp_path / "ckpt"

    def run(phase):
        return subprocess.run(
            [sys.executable, str(worker), str(ckpt_dir), phase],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "PYTHONPATH": str(worker.parents[2])},
        )

    assert run("baseline").returncode == 0, "baseline save failed"
    step1_npz = (ckpt_dir / "step_1.npz").read_bytes()
    step1_json = (ckpt_dir / "step_1.json").read_bytes()

    import signal as _signal

    for phase in ("mid_payload", "pre_sidecar"):
        proc = run(phase)
        assert proc.returncode == -_signal.SIGKILL, (phase, proc.stderr[-500:])
        manager = CheckpointManager(str(ckpt_dir), max_to_keep=10)
        assert manager.valid_steps() == [1], phase
        assert manager.latest_step() == 1, phase
        # the partial step never becomes a visible, torn checkpoint
        if phase == "mid_payload":
            assert (ckpt_dir / "step_2.npz.tmp").exists()
            assert not (ckpt_dir / "step_2.npz").exists()
        else:
            assert (ckpt_dir / "step_2.npz").exists()  # payload published...
            assert not (ckpt_dir / "step_2.json").exists()  # ...never committed
        # the prior step's files are byte-identical and restore exactly
        assert (ckpt_dir / "step_1.npz").read_bytes() == step1_npz, phase
        assert (ckpt_dir / "step_1.json").read_bytes() == step1_json, phase
        import importlib.util

        spec = importlib.util.spec_from_file_location("ckpt_kill_worker", worker)
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        expected = module.make_tree(1)
        restored = manager.restore(
            {k: np.zeros_like(v) for k, v in expected.items()}, step=1
        )
        for key in expected:
            np.testing.assert_array_equal(restored[key], expected[key])
        # cleanup for the next phase: kill the stray step-2 leftovers
        for leftover in ckpt_dir.glob("step_2*"):
            leftover.unlink()


@pytest.mark.jax
def test_process_metadata_sidecar_roundtrip_and_rotation(tmp_path):
    """Per-process sidecars: written atomically by each rank, read back by
    the same rank, and rotated away with their step."""
    manager = CheckpointManager(str(tmp_path / "run"), max_to_keep=1)
    tree = {"w": jnp.ones(3)}
    cursor = {"stream_cursor": {"epoch": 0, "slab": 2, "rows": 8, "batches": 5}}
    manager.save(1, tree, process_metadata=cursor)
    assert manager.process_metadata(1) == cursor
    assert manager.process_metadata(1, process_index=7) == {}  # another rank's
    assert manager.process_metadata(99) == {}  # absent step
    manager.save(2, tree, process_metadata={"stream_cursor": {"batches": 9}})
    assert manager.all_steps() == [2]  # step 1 rotated out...
    assert manager.process_metadata(1) == {}  # ...with its process sidecar
    assert manager.process_metadata(2)["stream_cursor"]["batches"] == 9


@pytest.mark.jax
def test_fit_saves_checkpoints(tmp_path):
    trainer = make_trainer()
    manager = CheckpointManager(str(tmp_path / "fit"), max_to_keep=5)
    batches = [make_batch(i) for i in range(3)]
    state = trainer.fit(lambda epoch: batches, epochs=2, checkpoint_manager=manager)
    assert manager.latest_step() == int(state.step)
    assert len(manager.history()) == 2

@pytest.mark.jax
def test_best_checkpoint_survives_rotation(tmp_path):
    manager = CheckpointManager(str(tmp_path / "run"), max_to_keep=2)
    tree = {"w": jnp.ones(2)}
    manager.save(1, tree)
    manager.mark_best(1)
    for step in (2, 3, 4, 5):
        manager.save(step, {"w": jnp.ones(2) * step})
    assert 1 in manager.all_steps()  # the best survives max_to_keep=2
    assert manager.best_step() == 1
    best = manager.restore_best({"w": np.zeros(2)})
    np.testing.assert_array_equal(best["w"], np.ones(2))


@pytest.mark.jax
def test_orbax_backend_roundtrip_and_rotation(tmp_path):
    """The orbax storage backend round-trips TrainStates and rotates cleanly."""
    pytest.importorskip("orbax.checkpoint")
    trainer = make_trainer()
    state = trainer.init_state(make_batch(0))
    state, _ = trainer.train_step(state, make_batch(0))
    manager = CheckpointManager(str(tmp_path / "orbax_run"), max_to_keep=2, backend="orbax")
    for step in (1, 2, 3):
        manager.save(step, state)
    assert manager.all_steps() == [2, 3]  # rotation removed the orbax dir too
    assert not (tmp_path / "orbax_run" / "step_1.orbax").exists()
    template = trainer.init_state(make_batch(0))
    restored = manager.restore(template)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6),
        restored.params,
        state.params,
    )
    with pytest.raises(ValueError, match="backend"):
        from replay_tpu.utils.checkpoint import save_pytree
        save_pytree(str(tmp_path / "x"), {"a": jnp.ones(2)}, backend="zzz")


@pytest.mark.jax
def test_restore_rejects_dtype_mismatch(tmp_path):
    """A checkpoint saved from a different-precision config is a hard error,
    not a silent mixed-precision restore."""
    save_pytree(str(tmp_path / "f32"), {"w": jnp.ones((2, 2), jnp.float32)})
    with pytest.raises(ValueError, match="dtype"):
        restore_pytree(str(tmp_path / "f32"), {"w": np.zeros((2, 2), np.float16)})


@pytest.mark.jax
def test_orbax_abstract_target_carries_sharding(tmp_path):
    """Orbax restore targets built from live jax.Arrays keep their sharding, so
    restore does not fall back to (topology-unsafe) sharding-from-file."""
    pytest.importorskip("orbax.checkpoint")
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh()
    tree = {"w": jax.device_put(jnp.ones((4, 4)), NamedSharding(mesh, P()))}
    save_pytree(str(tmp_path / "s"), tree, backend="orbax")
    with np.errstate(all="ignore"):
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("error", UserWarning)  # sharding-from-file warns
            restored = restore_pytree(str(tmp_path / "s"), tree)
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.ones((4, 4)))


@pytest.mark.jax
def test_trainer_save_checkpoint_backend_param(tmp_path):
    """Trainer.save_checkpoint honors an explicit backend choice."""
    pytest.importorskip("orbax.checkpoint")
    trainer = make_trainer()
    state = trainer.init_state(make_batch(0))
    trainer.save_checkpoint(str(tmp_path / "ck"), state, backend="orbax")
    assert (tmp_path / "ck.orbax").exists()
    restored = trainer.restore_checkpoint(str(tmp_path / "ck"), make_batch(0))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6),
        restored.params,
        state.params,
    )


@pytest.mark.jax
def test_mid_epoch_exact_resume(tmp_path):
    """A run killed mid-epoch and resumed reproduces the uninterrupted run's
    final parameters EXACTLY: the checkpoint records the data-iterator position
    (epoch + step within epoch) and fit fast-forwards the deterministic
    batch stream to it."""

    def train_batches(epoch: int):
        # deterministic per-epoch stream (the SequenceBatcher set_epoch contract)
        return [make_batch(epoch * 100 + i) for i in range(7)]

    # uninterrupted reference run: 2 epochs, mid-epoch checkpoints every 3 steps
    trainer_a = make_trainer()
    manager_a = CheckpointManager(str(tmp_path / "a"), max_to_keep=100)
    state_a = trainer_a.fit(
        train_batches, epochs=2, checkpoint_manager=manager_a, checkpoint_every=3,
    )

    # simulate the kill: keep only checkpoints up to mid-epoch-1-step-3
    # (epoch 1 = second epoch; 7 steps/epoch -> global step 10)
    manager_b = CheckpointManager(str(tmp_path / "b"), max_to_keep=100)
    import shutil

    for step in manager_a.all_steps():
        if step <= 10:
            for suffix in (".npz", ".json"):
                src = (tmp_path / "a" / f"step_{step}").with_suffix(suffix)
                if src.exists():
                    shutil.copy(src, tmp_path / "b" / src.name)
    assert manager_b.latest_step() == 10
    from replay_tpu.utils.checkpoint import load_metadata

    meta = load_metadata(str(tmp_path / "b" / "step_10"))
    assert meta["mid_epoch"] and meta["epoch"] == 1 and meta["step_in_epoch"] == 3

    # resume in a FRESH trainer: restores step 10, fast-forwards 3 batches of
    # epoch 1, finishes the run
    trainer_b = make_trainer()
    state_b = trainer_b.fit(
        train_batches, epochs=2, checkpoint_manager=manager_b,
        checkpoint_every=3, resume=True,
    )
    assert int(state_b.step) == int(state_a.step)
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)),
        state_a.params,
        state_b.params,
    )
    # optimizer state and rng resume exactly too
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)),
        state_a.opt_state,
        state_b.opt_state,
    )
    np.testing.assert_array_equal(np.asarray(state_a.rng), np.asarray(state_b.rng))


@pytest.mark.jax
def test_resume_from_epoch_end_checkpoint(tmp_path):
    """Resume from an epoch-boundary checkpoint starts at the NEXT epoch."""

    def train_batches(epoch: int):
        return [make_batch(epoch * 10 + i) for i in range(3)]

    trainer_a = make_trainer()
    manager_a = CheckpointManager(str(tmp_path / "a"), max_to_keep=100)
    state_a = trainer_a.fit(train_batches, epochs=3, checkpoint_manager=manager_a)

    manager_b = CheckpointManager(str(tmp_path / "b"), max_to_keep=100)
    import shutil

    for step in manager_a.all_steps():
        if step <= 6:  # epochs 0 and 1 complete
            for suffix in (".npz", ".json"):
                src = (tmp_path / "a" / f"step_{step}").with_suffix(suffix)
                if src.exists():
                    shutil.copy(src, tmp_path / "b" / src.name)
    trainer_b = make_trainer()
    state_b = trainer_b.fit(
        train_batches, epochs=3, checkpoint_manager=manager_b, resume=True
    )
    assert int(state_b.step) == int(state_a.step)
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)),
        state_a.params,
        state_b.params,
    )


@pytest.mark.jax
def test_resume_requires_manager():
    trainer = make_trainer()
    with pytest.raises(ValueError, match="checkpoint_manager"):
        trainer.fit([make_batch(0)], resume=True)


@pytest.mark.jax
def test_resume_preserves_monitored_best(tmp_path):
    """A resumed run must not let a worse post-resume epoch steal best.json or
    the returned state: best_value is seeded from the restored history and the
    pre-kill best checkpoint wins when nothing beats it."""

    def scrambled_batch(seed: int) -> dict:
        # labels decoupled from inputs: unlearnable, so its loss stays HIGH
        batch = make_batch(seed)
        rng = np.random.default_rng(seed + 999)
        batch["positive_labels"] = rng.integers(
            0, NUM_ITEMS, batch["positive_labels"].shape
        ).astype(np.int32)
        return batch

    def train_batches(epoch: int):
        if epoch >= 2:  # the post-resume epoch is deliberately WORSE
            return [scrambled_batch(epoch * 10 + i) for i in range(3)]
        return [make_batch(epoch * 10 + i) for i in range(3)]

    # run 2 learnable epochs with the monitored best recorded on disk. LR 0.1:
    # at 1e-2 six steps barely move the loss off init, leaving it ABOVE the
    # scrambled epoch's ~log(NUM_ITEMS) random-label floor — the scenario's
    # "worse epoch" premise needs the learnable epochs to actually learn
    trainer_a = make_trainer(learning_rate=0.1)
    manager = CheckpointManager(str(tmp_path / "run"), max_to_keep=100)
    trainer_a.fit(
        train_batches, epochs=2, checkpoint_manager=manager, monitor="train_loss",
        mode="min",
    )
    best_before = manager.best_step()
    best_loss_before = min(r["train_loss"] for r in trainer_a.history)

    # resume into the scrambled epoch: its loss is worse, so the pre-kill best
    # must survive both in best.json and as the returned state
    trainer_b = make_trainer(learning_rate=0.1)
    state_b = trainer_b.fit(
        train_batches, epochs=3, checkpoint_manager=manager, monitor="train_loss",
        mode="min", resume=True,
    )
    assert trainer_b.history[-1]["train_loss"] > best_loss_before
    assert manager.best_step() == best_before
    reference_best = manager.restore(state_b, step=best_before)
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)),
        reference_best.params,
        state_b.params,
    )


@pytest.mark.jax
def test_resume_monitored_best_survives_lost_history(tmp_path):
    """history.json lost (cleanup, torn filesystem): the monitored-best seed
    falls back to the best checkpoint's sidecar metadata — the same channel
    lr_scale resumes through — so a worse post-resume epoch still cannot
    repoint best.json or win the returned state."""

    def scrambled_batch(seed: int) -> dict:
        batch = make_batch(seed)
        rng = np.random.default_rng(seed + 999)
        batch["positive_labels"] = rng.integers(
            0, NUM_ITEMS, batch["positive_labels"].shape
        ).astype(np.int32)
        return batch

    def train_batches(epoch: int):
        if epoch >= 2:  # the post-resume epoch is deliberately worse
            return [scrambled_batch(epoch * 10 + i) for i in range(3)]
        return [make_batch(epoch * 10 + i) for i in range(3)]

    trainer_a = make_trainer(learning_rate=0.1)
    manager = CheckpointManager(str(tmp_path / "run"), max_to_keep=100)
    trainer_a.fit(
        train_batches, epochs=2, checkpoint_manager=manager, monitor="train_loss",
        mode="min",
    )
    best_before = manager.best_step()
    (tmp_path / "run" / "history.json").unlink()  # the history record is gone
    assert manager.metadata(best_before)["train_loss"] is not None  # sidecar survives

    trainer_b = make_trainer(learning_rate=0.1)
    state_b = trainer_b.fit(
        train_batches, epochs=3, checkpoint_manager=manager, monitor="train_loss",
        mode="min", resume=True,
    )
    assert manager.best_step() == best_before
    reference_best = manager.restore(state_b, step=best_before)
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)),
        reference_best.params,
        state_b.params,
    )


@pytest.mark.jax
def test_resume_with_explicit_state_rejected(tmp_path):
    trainer = make_trainer()
    manager = CheckpointManager(str(tmp_path / "m"))
    state = trainer.init_state(make_batch(0))
    with pytest.raises(ValueError, match="ambiguous"):
        trainer.fit(
            [make_batch(0)], state=state, checkpoint_manager=manager, resume=True
        )


@pytest.mark.jax
def test_resume_already_complete_returns_checkpoint(tmp_path):
    def train_batches(epoch: int):
        return [make_batch(epoch * 10 + i) for i in range(3)]

    trainer_a = make_trainer()
    manager = CheckpointManager(str(tmp_path / "done"), max_to_keep=100)
    state_a = trainer_a.fit(train_batches, epochs=2, checkpoint_manager=manager)

    trainer_b = make_trainer()
    state_b = trainer_b.fit(
        train_batches, epochs=2, checkpoint_manager=manager, resume=True
    )
    assert int(state_b.step) == int(state_a.step)
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)),
        state_a.params,
        state_b.params,
    )


@pytest.mark.jax
def test_resume_when_best_step_points_at_deleted_step(tmp_path):
    """best.json referencing a step whose files were deleted (manual cleanup,
    over-eager retention) is stale, not fatal: best_step() returns None and a
    monitored resume completes, re-deriving the best from the restored
    history."""

    def train_batches(epoch: int):
        return [make_batch(epoch * 10 + i) for i in range(3)]

    trainer_a = make_trainer()
    manager = CheckpointManager(str(tmp_path / "run"), max_to_keep=100)
    trainer_a.fit(
        train_batches, epochs=2, checkpoint_manager=manager, monitor="train_loss",
        mode="min",
    )
    best = manager.best_step()
    assert best is not None
    manager._delete_step(best)  # best.json now dangles
    assert manager.best_step() is None

    trainer_b = make_trainer()
    state_b = trainer_b.fit(
        train_batches, epochs=3, checkpoint_manager=manager, monitor="train_loss",
        mode="min", resume=True,
    )
    # the deleted best forced the resume back to the previous checkpoint, so
    # its epoch is replayed (one duplicate record); the run then completes
    assert trainer_b.history[-1]["epoch"] == 2
    assert np.isfinite(trainer_b.history[-1]["train_loss"])
    assert int(state_b.step) > 0
    assert manager.best_step() is not None  # a fresh best was re-marked


@pytest.mark.jax
def test_resume_after_interrupted_final_save(tmp_path):
    """A run whose final save was interrupted (truncated payload) resumes from
    the previous intact checkpoint and reproduces the uninterrupted final
    state exactly."""
    from replay_tpu.utils.faults import truncate_file

    def train_batches(epoch: int):
        return [make_batch(epoch * 10 + i) for i in range(3)]

    trainer_a = make_trainer()
    manager = CheckpointManager(str(tmp_path / "run"), max_to_keep=100)
    state_a = trainer_a.fit(train_batches, epochs=2, checkpoint_manager=manager)
    final = manager.latest_step()
    truncate_file(str(tmp_path / "run" / f"step_{final}.npz"), keep_fraction=0.5)

    assert manager.latest_step() == 3  # epoch-0 checkpoint survives the scan
    assert manager.skipped_steps == [final]
    trainer_b = make_trainer()
    state_b = trainer_b.fit(
        train_batches, epochs=2, checkpoint_manager=manager, resume=True
    )
    assert int(state_b.step) == int(state_a.step)
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)),
        state_a.params,
        state_b.params,
    )


@pytest.mark.jax
def test_resume_already_complete_returns_monitored_best(tmp_path):
    """When the finished run tracked a monitor, re-running with resume=True must
    hand back the BEST checkpoint (what the original fit returned), not the
    latest one."""

    def scrambled_batch(seed: int) -> dict:
        batch = make_batch(seed)
        rng = np.random.default_rng(seed + 999)
        batch["positive_labels"] = rng.integers(
            0, NUM_ITEMS, batch["positive_labels"].shape
        ).astype(np.int32)
        return batch

    def train_batches(epoch: int):
        if epoch >= 2:  # the final epoch is deliberately worse
            return [scrambled_batch(epoch * 10 + i) for i in range(3)]
        return [make_batch(epoch * 10 + i) for i in range(3)]

    # LR 0.1 (not the default 1e-2) so the learnable epochs genuinely beat the
    # scrambled epoch's random-label loss floor — see
    # test_resume_preserves_monitored_best
    trainer_a = make_trainer(learning_rate=0.1)
    manager = CheckpointManager(str(tmp_path / "done_best"), max_to_keep=100)
    state_a = trainer_a.fit(
        train_batches, epochs=3, checkpoint_manager=manager, monitor="train_loss",
        mode="min",
    )
    best_step = manager.best_step()
    assert best_step is not None and best_step != manager.latest_step()
    assert int(state_a.step) == best_step  # fit returned the best, not latest

    trainer_b = make_trainer(learning_rate=0.1)
    state_b = trainer_b.fit(
        train_batches, epochs=3, checkpoint_manager=manager, monitor="train_loss",
        mode="min", resume=True,
    )
    assert int(state_b.step) == best_step
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)),
        state_a.params,
        state_b.params,
    )
