"""Serve-side chaos injectors (utils/faults): deterministic, restorable (core).

The stream injectors (NaNInjector / SignalAtStep) are exercised through the
trainer's fault-tolerance suite; these cover the callable injectors the serve
chaos harness wraps around ``ScoringEngine`` methods.
"""

import time

import pytest

from replay_tpu.utils.faults import (
    EngineErrorAt,
    InjectedFault,
    LatencySpike,
    wrap_method,
)


class TestEngineErrorAt:
    def test_raises_at_chosen_call_indices_only(self):
        injector = EngineErrorAt(at_calls=[1, 3])
        calls = []
        wrapped = injector.wrap(lambda x: calls.append(x) or x * 2)
        assert wrapped(1) == 2
        with pytest.raises(InjectedFault, match="call 1"):
            wrapped(2)
        assert wrapped(3) == 6
        with pytest.raises(InjectedFault):
            wrapped(4)
        assert wrapped(5) == 10
        assert injector.injected_at == [1, 3]
        assert calls == [1, 3, 5]  # injected calls never reach the target

    def test_positions_are_global_across_wrap_targets(self):
        """Like the stream injectors' global batch indices: one instance, one
        position counter, regardless of how many callables it wraps."""
        injector = EngineErrorAt(at_calls=[2])
        first = injector.wrap(lambda: "a")
        second = injector.wrap(lambda: "b")
        assert first() == "a"  # 0
        assert second() == "b"  # 1
        with pytest.raises(InjectedFault):
            first()  # 2 — global index, not per-wrap
        assert injector.injected_at == [2]

    def test_injected_fault_is_distinguishable(self):
        """Chaos accounting depends on telling injected faults from organic
        failures — InjectedFault must be its own type."""
        assert issubclass(InjectedFault, RuntimeError)
        injector = EngineErrorAt(at_calls=[0])
        with pytest.raises(InjectedFault):
            injector.wrap(lambda: None)()


class TestLatencySpike:
    def test_delays_at_chosen_calls_without_changing_results(self):
        spike = LatencySpike(at_calls=[1], duration_s=0.08)
        wrapped = spike.wrap(lambda x: x + 1)
        start = time.perf_counter()
        assert wrapped(1) == 2
        fast = time.perf_counter() - start
        start = time.perf_counter()
        assert wrapped(2) == 3  # the spiked call still returns the real result
        slow = time.perf_counter() - start
        assert slow >= 0.08
        assert fast < slow
        assert spike.injected_at == [1]


class TestWrapMethod:
    def test_patches_instance_and_returns_original_for_restore(self):
        class Engine:
            def encode(self, x):
                return x * 10

        engine = Engine()
        original = wrap_method(engine, "encode", EngineErrorAt(at_calls=[0]))
        with pytest.raises(InjectedFault):
            engine.encode(1)
        assert engine.encode(2) == 20  # past the injection window
        engine.encode = original
        assert engine.encode(1) == 10
        # instance patch only — the class is untouched
        assert Engine().encode(1) == 10
