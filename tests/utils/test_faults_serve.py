"""Serve-side chaos injectors (utils/faults): deterministic, restorable (core).

The stream injectors (NaNInjector / SignalAtStep) are exercised through the
trainer's fault-tolerance suite; these cover the callable injectors the serve
chaos harness wraps around ``ScoringEngine`` methods.
"""

import signal
import subprocess
import sys
import time

import pytest

from replay_tpu.utils.faults import (
    EngineErrorAt,
    InjectedFault,
    KillAtStep,
    LatencySpike,
    wrap_method,
)


class TestEngineErrorAt:
    def test_raises_at_chosen_call_indices_only(self):
        injector = EngineErrorAt(at_calls=[1, 3])
        calls = []
        wrapped = injector.wrap(lambda x: calls.append(x) or x * 2)
        assert wrapped(1) == 2
        with pytest.raises(InjectedFault, match="call 1"):
            wrapped(2)
        assert wrapped(3) == 6
        with pytest.raises(InjectedFault):
            wrapped(4)
        assert wrapped(5) == 10
        assert injector.injected_at == [1, 3]
        assert calls == [1, 3, 5]  # injected calls never reach the target

    def test_positions_are_global_across_wrap_targets(self):
        """Like the stream injectors' global batch indices: one instance, one
        position counter, regardless of how many callables it wraps."""
        injector = EngineErrorAt(at_calls=[2])
        first = injector.wrap(lambda: "a")
        second = injector.wrap(lambda: "b")
        assert first() == "a"  # 0
        assert second() == "b"  # 1
        with pytest.raises(InjectedFault):
            first()  # 2 — global index, not per-wrap
        assert injector.injected_at == [2]

    def test_injected_fault_is_distinguishable(self):
        """Chaos accounting depends on telling injected faults from organic
        failures — InjectedFault must be its own type."""
        assert issubclass(InjectedFault, RuntimeError)
        injector = EngineErrorAt(at_calls=[0])
        with pytest.raises(InjectedFault):
            injector.wrap(lambda: None)()


class TestLatencySpike:
    def test_delays_at_chosen_calls_without_changing_results(self):
        spike = LatencySpike(at_calls=[1], duration_s=0.08)
        wrapped = spike.wrap(lambda x: x + 1)
        start = time.perf_counter()
        assert wrapped(1) == 2
        fast = time.perf_counter() - start
        start = time.perf_counter()
        assert wrapped(2) == 3  # the spiked call still returns the real result
        slow = time.perf_counter() - start
        assert slow >= 0.08
        assert fast < slow
        assert spike.injected_at == [1]


class TestKillAtStep:
    def test_wrap_sigkills_own_process_at_the_step(self, tmp_path):
        """The hard-kill contract: the child dies with SIGKILL mid-stream,
        no cleanup runs, and exactly ``at_step`` batches made it out."""
        progress = tmp_path / "progress.txt"
        script = (
            "import atexit, sys\n"
            "from replay_tpu.utils.faults import KillAtStep\n"
            "atexit.register(lambda: sys.stderr.write('CLEANUP RAN\\n'))\n"
            "with open(sys.argv[1], 'w') as fh:\n"
            "    for batch in KillAtStep(at_step=3).wrap(iter(range(10))):\n"
            "        fh.write(f'{batch}\\n')\n"
            "        fh.flush()\n"
            "sys.stderr.write('SURVIVED\\n')\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", script, str(progress)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == -signal.SIGKILL
        assert "SURVIVED" not in proc.stderr
        assert "CLEANUP RAN" not in proc.stderr  # SIGKILL: no handlers, no atexit
        assert progress.read_text().split() == ["0", "1", "2"]

    def test_fire_kills_a_target_pid(self):
        """The fleet-chaos mode: retarget an arbitrary replica process."""
        victim = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(60)"]
        )
        try:
            injector = KillAtStep(pid=victim.pid)
            injector.fire()
            assert victim.wait(timeout=30) == -signal.SIGKILL
            assert injector.fired
        finally:
            if victim.poll() is None:
                victim.kill()
                victim.wait()

    def test_fires_at_most_once(self):
        """A SIGTERM-tolerant double-iteration must not re-kill: ``fired``
        latches (mirrors SignalAtStep.raised)."""
        sent = []
        injector = KillAtStep(at_step=1, pid=99999999, sig=signal.SIGKILL)
        injector.fire = lambda: (sent.append(1), setattr(injector, "fired", True))
        assert list(injector.wrap(iter(range(4)))) == [0, 1, 2, 3]
        assert sent == [1]


class TestWrapMethod:
    def test_patches_instance_and_returns_original_for_restore(self):
        class Engine:
            def encode(self, x):
                return x * 10

        engine = Engine()
        original = wrap_method(engine, "encode", EngineErrorAt(at_calls=[0]))
        with pytest.raises(InjectedFault):
            engine.encode(1)
        assert engine.encode(2) == 20  # past the injection window
        engine.encode = original
        assert engine.encode(1) == 10
        # instance patch only — the class is untouched
        assert Engine().encode(1) == 10
