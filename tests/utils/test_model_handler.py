"""Generic .replay persistence dispatchers (utils/model_handler.py)."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.data.dataset import Dataset
from replay_tpu.data.dataset_label_encoder import DatasetLabelEncoder
from replay_tpu.data.schema import FeatureHint, FeatureInfo, FeatureSchema, FeatureType
from replay_tpu.models import PopRec
from replay_tpu.splitters import LastNSplitter, RatioSplitter
from replay_tpu.utils import (
    load,
    load_encoder,
    load_from_replay,
    load_splitter,
    save,
    save_encoder,
    save_splitter,
    save_to_replay,
)


@pytest.fixture
def log():
    return pd.DataFrame(
        {
            "query_id": ["u1", "u1", "u2", "u3", "u3", "u3"],
            "item_id": ["a", "b", "a", "a", "b", "c"],
            "rating": [1.0, 1.0, 1.0, 1.0, 1.0, 1.0],
            "timestamp": range(6),
        }
    )


@pytest.fixture
def dataset(log):
    schema = FeatureSchema(
        [
            FeatureInfo("query_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
            FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
            FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING),
            FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP),
        ]
    )
    return Dataset(feature_schema=schema, interactions=log)


class TestGenericSaveLoad:
    def test_model_roundtrip_without_knowing_class(self, dataset, tmp_path):
        encoded = DatasetLabelEncoder().fit_transform(dataset)
        model = PopRec().fit(encoded)
        save(model, tmp_path / "pop")
        restored = load(tmp_path / "pop")  # no model_type given
        assert type(restored).__name__ == "PopRec"
        orig = model.predict(encoded, k=2)
        back = restored.predict(encoded, k=2)
        pd.testing.assert_frame_equal(
            orig.reset_index(drop=True), back.reset_index(drop=True)
        )

    def test_overwrite_guard(self, dataset, tmp_path):
        encoded = DatasetLabelEncoder().fit_transform(dataset)
        model = PopRec().fit(encoded)
        save(model, tmp_path / "pop")
        with pytest.raises(FileExistsError, match="overwrite=True"):
            save(model, tmp_path / "pop")
        save(model, tmp_path / "pop", overwrite=True)  # no raise

    def test_save_requires_save_method(self, tmp_path):
        with pytest.raises(TypeError, match="no .save"):
            save(object(), tmp_path / "x")

    def test_common_aliases(self):
        assert save_to_replay is save and load_from_replay is load

    def test_unknown_class_rejected(self, tmp_path):
        import json

        target = (tmp_path / "weird").with_suffix(".replay")
        target.mkdir()
        (target / "init_args.json").write_text(json.dumps({"_class_name": "NotAModel"}))
        with pytest.raises(ValueError, match="NotAModel"):
            load(tmp_path / "weird")


class TestSplitterRoundtrip:
    def test_ratio(self, tmp_path, log):
        splitter = RatioSplitter(test_size=0.5, divide_column="query_id")
        save_splitter(splitter, tmp_path / "sp")
        restored = load_splitter(tmp_path / "sp")
        assert isinstance(restored, RatioSplitter)
        train_a, test_a = splitter.split(log)
        train_b, test_b = restored.split(log)
        pd.testing.assert_frame_equal(train_a, train_b)
        pd.testing.assert_frame_equal(test_a, test_b)

    def test_last_n(self, tmp_path):
        splitter = LastNSplitter(N=2, divide_column="query_id")
        save_splitter(splitter, tmp_path / "sp2")
        restored = load_splitter(tmp_path / "sp2")
        assert isinstance(restored, LastNSplitter) and restored.N == 2

    def test_overwrite_guard(self, tmp_path):
        splitter = LastNSplitter(N=1)
        save_splitter(splitter, tmp_path / "sp3")
        with pytest.raises(FileExistsError):
            save_splitter(splitter, tmp_path / "sp3")

    def test_datetime_threshold(self, tmp_path, log):
        from datetime import datetime

        from replay_tpu.splitters import TimeSplitter

        splitter = TimeSplitter(time_threshold=datetime(1970, 1, 1, 0, 0, 3))
        save_splitter(splitter, tmp_path / "ts")
        restored = load_splitter(tmp_path / "ts")
        ts_log = log.assign(timestamp=pd.to_datetime(log["timestamp"], unit="s"))
        train_a, test_a = splitter.split(ts_log)
        train_b, test_b = restored.split(ts_log)
        pd.testing.assert_frame_equal(train_a, train_b)
        pd.testing.assert_frame_equal(test_a, test_b)

    def test_failed_save_leaves_no_artifact(self, tmp_path):
        splitter = LastNSplitter(N=1)
        splitter.N = object()  # unserializable init arg
        with pytest.raises(TypeError):
            save_splitter(splitter, tmp_path / "broken")
        assert not (tmp_path / "broken.replay").exists()
        splitter.N = 1
        save_splitter(splitter, tmp_path / "broken")  # retry must succeed


class TestEncoderRoundtrip:
    def test_fitted_encoder(self, dataset, tmp_path, log):
        encoder = DatasetLabelEncoder().fit(dataset)
        save_encoder(encoder, tmp_path / "enc")
        restored = load_encoder(tmp_path / "enc")
        out_a = encoder.transform(dataset).interactions
        out_b = restored.transform(dataset).interactions
        pd.testing.assert_frame_equal(out_a, out_b)
        assert restored.query_id_encoder.mapping == encoder.query_id_encoder.mapping
        assert restored.item_id_encoder.mapping == encoder.item_id_encoder.mapping
