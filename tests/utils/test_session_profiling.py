"""Session state, logging configuration, profiling hooks."""

import logging

import numpy as np
import pytest

from replay_tpu.utils import State, StepTimer, get_default_mesh, setup_logging, trace


def test_setup_logging_idempotent():
    logger = setup_logging("WARNING")
    assert logger.level == logging.WARNING
    again = setup_logging("INFO")
    assert again is logger and again.level == logging.INFO
    assert len(logger.handlers) == 1  # no handler duplication


@pytest.mark.jax
def test_state_singleton_and_default_mesh():
    State.reset()
    a, b = State(), State()
    assert a is b
    mesh = get_default_mesh()
    assert mesh.shape["data"] * mesh.shape["model"] == len(a.devices)
    a.set_mesh("sentinel")
    assert State().mesh == "sentinel"
    State.reset()


@pytest.mark.jax
def test_step_timer():
    import jax.numpy as jnp

    timer = StepTimer(warmup_steps=2, samples_per_step=8)
    result = jnp.ones(())
    for _ in range(6):
        timer.tick(result)
    stats = timer.finish(result)
    assert stats["steps"] == 4
    assert stats["steps_per_sec"] > 0
    assert stats["samples_per_sec"] == pytest.approx(stats["steps_per_sec"] * 8)
    empty = StepTimer(warmup_steps=5)
    empty.tick()
    assert np.isnan(empty.finish()["steps_per_sec"])


@pytest.mark.jax
def test_trace_writes_profile(tmp_path):
    import jax
    import jax.numpy as jnp

    with trace(str(tmp_path / "prof")):
        jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    produced = list((tmp_path / "prof").rglob("*"))
    assert produced  # a trace directory with events was written
