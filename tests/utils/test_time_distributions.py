"""Time smoothing (utils/time.py) and item_distribution (utils/distributions.py)."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.utils import get_item_recency, item_distribution, smoothe_time


@pytest.fixture
def five_row_log():
    return pd.DataFrame(
        {
            "item_id": [1, 1, 2, 3, 3],
            "timestamp": ["2099-03-19", "2099-03-20", "2099-03-22", "2099-03-27", "2099-03-25"],
            "rating": [1.0, 1.0, 1.0, 1.0, 1.0],
        }
    )


class TestSmootheTime:
    # expected values are the reference's doctest outputs
    # (replay/utils/time.py:147-231) — behavior parity fixtures.
    def test_power(self, five_row_log):
        out = smoothe_time(five_row_log, kind="power").sort_values("timestamp")
        assert out["rating"].round(4).tolist() == [0.639, 0.6546, 0.6941, 0.7994, 1.0]

    def test_exp(self, five_row_log):
        out = smoothe_time(five_row_log, kind="exp").sort_values("timestamp")
        assert out["rating"].round(4).tolist() == [0.8312, 0.8507, 0.8909, 0.9548, 1.0]

    def test_linear(self, five_row_log):
        out = smoothe_time(five_row_log, kind="linear").sort_values("timestamp")
        assert out["rating"].round(4).tolist() == [0.8667, 0.8833, 0.9167, 0.9667, 1.0]

    def test_scales_existing_rating(self):
        df = pd.DataFrame(
            {
                "item_id": [1, 2, 3],
                "timestamp": ["2099-03-19", "2099-03-20", "2099-03-22"],
                "rating": [10.0, 3.0, 0.1],
            }
        )
        out = smoothe_time(df)
        assert out["rating"].round(4).tolist() == [9.3303, 2.8645, 0.1]

    def test_limit_floor(self):
        df = pd.DataFrame(
            {
                "item_id": [1, 2],
                "timestamp": ["2000-01-01", "2099-01-01"],
                "rating": [1.0, 1.0],
            }
        )
        out = smoothe_time(df, decay=2, limit=0.25, kind="exp")
        assert out["rating"].tolist() == [0.25, 1.0]

    def test_numeric_timestamps(self):
        df = pd.DataFrame(
            {"item_id": [1, 2], "timestamp": [0, 86400 * 30], "rating": [1.0, 1.0]}
        )
        out = smoothe_time(df, decay=30, kind="exp")
        assert out["rating"].round(6).tolist() == [0.5, 1.0]

    def test_input_not_mutated(self, five_row_log):
        before = five_row_log.copy()
        smoothe_time(five_row_log)
        pd.testing.assert_frame_equal(five_row_log, before)

    def test_bad_kind_raises(self, five_row_log):
        with pytest.raises(ValueError, match="kind"):
            smoothe_time(five_row_log, kind="log")

    def test_bad_decay_raises(self, five_row_log):
        with pytest.raises(ValueError, match="decay"):
            smoothe_time(five_row_log, decay=1.0)


class TestGetItemRecency:
    def test_power(self, five_row_log):
        out = get_item_recency(five_row_log, kind="power").sort_values("item_id")
        # reference doctest: item means 03-19 12:00 / 03-22 / 03-26
        assert out["rating"].round(4).tolist() == [0.6632, 0.7204, 1.0]

    def test_one_row_per_item(self, five_row_log):
        out = get_item_recency(five_row_log)
        assert sorted(out["item_id"].tolist()) == [1, 2, 3]

    def test_ratings_ignored(self, five_row_log):
        loud = five_row_log.assign(rating=[100.0, 1.0, 5.0, 0.1, 2.0])
        pd.testing.assert_frame_equal(
            get_item_recency(five_row_log), get_item_recency(loud)
        )

    def test_numeric_timestamps_stay_numeric(self):
        df = pd.DataFrame(
            {"item_id": [1, 2], "timestamp": [0, 86400 * 30], "rating": [1.0, 1.0]}
        )
        out = get_item_recency(df, decay=30, kind="exp")
        assert pd.api.types.is_numeric_dtype(out["timestamp"])
        assert out["timestamp"].tolist() == [0.0, 86400.0 * 30]
        assert out["rating"].round(6).tolist() == [0.5, 1.0]


class TestItemDistribution:
    def test_counts(self):
        log = pd.DataFrame(
            {
                "query_id": [1, 1, 2, 3, 3, 3],
                "item_id": [10, 11, 10, 10, 11, 12],
                "rating": [1.0] * 6,
            }
        )
        recs = pd.DataFrame(
            {
                "query_id": [1, 1, 1, 2, 2],
                "item_id": [10, 11, 13, 11, 13],
                "rating": [3.0, 2.0, 1.0, 9.0, 8.0],
            }
        )
        out = item_distribution(log, recs, k=2)
        by_item = out.set_index("item_id")
        # item 13 never in log; item 12 never recommended; k=2 truncates
        # user 1's third rec (item 13 at rank 3).
        assert by_item.loc[10, "user_count"] == 3 and by_item.loc[10, "rec_count"] == 1
        assert by_item.loc[11, "user_count"] == 2 and by_item.loc[11, "rec_count"] == 2
        assert by_item.loc[12, "user_count"] == 1 and by_item.loc[12, "rec_count"] == 0
        assert by_item.loc[13, "user_count"] == 0 and by_item.loc[13, "rec_count"] == 1

    def test_sorted_by_popularity(self):
        log = pd.DataFrame(
            {"query_id": [1, 2, 3, 1], "item_id": [5, 5, 5, 6], "rating": [1.0] * 4}
        )
        recs = pd.DataFrame({"query_id": [1], "item_id": [5], "rating": [1.0]})
        out = item_distribution(log, recs, k=1)
        assert out["user_count"].is_monotonic_increasing
